"""Rule engine: parse files, run rule visitors, apply inline suppressions.

A rule is an object with ``rule_id``, ``severity``, ``description`` and a
``check(tree, ctx)`` generator yielding :class:`~petastorm_tpu.analysis.findings.Finding`.
``ctx`` is a :class:`FileContext` carrying the source text, path, a lazily built
child→parent node map, and helpers shared by several rules (import-alias
resolution, source-line extraction).

Two phases share every parsed tree (ISSUE 16). The per-file phase runs each
:class:`Rule` over one module at a time; the whole-program phase then builds a
:class:`~petastorm_tpu.analysis.project.ProjectContext` over the SAME
``FileContext`` objects — no file is read or parsed twice — and runs each
:class:`ProjectRule` once across the corpus. Findings from both phases flow
through the same inline-suppression and baseline machinery: a project-phase
finding lands on a concrete file/line, so ``# graftlint: disable=GL-C005`` and
baseline entries behave identically for it.

Inline suppressions (documented in docs/static_analysis.md):

- ``# graftlint: disable=GL-C001`` (comma-separated ids, or ``all``) on the
  flagged line suppresses findings on that line;
- ``# graftlint: disable-file=GL-J001`` anywhere in the file suppresses the
  named rules (or ``all``) for the whole file.
"""
from __future__ import annotations

import ast
import io
import os
import re
import tokenize

from petastorm_tpu.analysis.findings import Finding, Severity

_SUPPRESS_LINE = re.compile(r"#\s*graftlint:\s*disable=([\w\-,]+)")
_SUPPRESS_FILE = re.compile(r"#\s*graftlint:\s*disable-file=([\w\-,]+)")


class FileContext:
    """Per-file state shared by rule visitors."""

    def __init__(self, path, source, tree):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self._parents = None
        self._numpy_aliases = None
        self._walk_cache = None
        self._type_index = None
        #: cross-rule memoization slot (e.g. the tracing rules' jit index):
        #: three rules needing the same derived index compute it once
        self.cache = {}

    def walk(self):
        """Every node of the tree, walked ONCE and cached. The rules iterate
        this instead of re-running ``ast.walk(tree)`` each — with ~16 per-file
        rules plus the project phase, repeated full walks were the analyzer's
        dominant cost (not parsing)."""
        if self._walk_cache is None:
            self._walk_cache = list(ast.walk(self.tree))
        return self._walk_cache

    def by_type(self, *types):
        """All nodes of the given AST type(s), from a bucket index built once
        per file. Order follows the cached walk (breadth-first, same as
        ``ast.walk``)."""
        if self._type_index is None:
            index = {}
            for node in self.walk():
                index.setdefault(type(node), []).append(node)
            self._type_index = index
        if len(types) == 1:
            return self._type_index.get(types[0], [])
        out = []
        for t in types:
            out.extend(self._type_index.get(t, []))
        return out

    @property
    def parents(self):
        """Child node → parent node map (built once per file)."""
        if self._parents is None:
            self._parents = {}
            for parent in self.walk():
                for child in ast.iter_child_nodes(parent):
                    self._parents[child] = parent
        return self._parents

    def parent(self, node):
        return self.parents.get(node)

    def code_at(self, line):
        """Stripped source text of a 1-based line (baseline fingerprint input)."""
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    @property
    def numpy_aliases(self):
        """Names the file binds to the numpy module (``import numpy as np`` …)."""
        if self._numpy_aliases is None:
            aliases = set()
            for node in self.by_type(ast.Import):
                for a in node.names:
                    if a.name == "numpy":
                        aliases.add(a.asname or "numpy")
            aliases.update({"np", "numpy"} & _module_like_names(self))
            self._numpy_aliases = aliases or {"np", "numpy"}
        return self._numpy_aliases

    def finding(self, rule, node, message, fix_hint=""):
        line = getattr(node, "lineno", 1)
        return Finding(
            rule_id=rule.rule_id,
            severity=rule.severity,
            path=self.path,
            line=line,
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
            fix_hint=fix_hint or rule.fix_hint,
            code=self.code_at(line),
            end_line=getattr(node, "end_lineno", None) or line,
        )


def _module_like_names(ctx):
    names = set()
    for node in ctx.by_type(ast.Import):
        for a in node.names:
            names.add((a.asname or a.name).split(".")[0])
    return names


class Rule:
    """Base rule: subclasses set the id/severity/description and implement check."""

    rule_id = "GL-X000"
    severity = Severity.ERROR
    description = ""
    fix_hint = ""

    def check(self, tree, ctx):
        raise NotImplementedError


class ProjectRule(Rule):
    """Whole-program rule: runs ONCE over the
    :class:`~petastorm_tpu.analysis.project.ProjectContext` built from every
    parsed file, after the per-file phase. Subclasses implement
    ``check_project(project)`` yielding Findings anchored at concrete
    file/line positions (so inline suppressions and the baseline apply
    unchanged)."""

    def check(self, tree, ctx):
        return iter(())  # project rules have no per-file phase

    def check_project(self, project):
        raise NotImplementedError


class ParseErrorRule(Rule):
    """Not a real visitor — the id under which unparseable files are reported."""

    rule_id = "GL-X001"
    severity = Severity.ERROR
    description = "file could not be parsed as Python"


def default_rules():
    from petastorm_tpu.analysis.rules import ALL_RULES

    return [cls() for cls in ALL_RULES]


def default_project_rules():
    from petastorm_tpu.analysis.rules import ALL_PROJECT_RULES

    return [cls() for cls in ALL_PROJECT_RULES]


def _suppressions(source):
    """(per-line {lineno: set(ids)}, file-wide set(ids)); 'all' means every rule.

    Matches COMMENT tokens only (via tokenize): a ``# graftlint: disable=...``
    inside a string literal — lint-fixture strings in the analyzer's own test
    suite, docstrings quoting the syntax — must NOT register a suppression."""
    per_line = {}
    per_file = set()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError, ValueError):
        return per_line, per_file  # ast.parse succeeded upstream; be safe anyway
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_FILE.search(tok.string)
        if m:
            per_file.update(x.strip() for x in m.group(1).split(","))
            continue
        m = _SUPPRESS_LINE.search(tok.string)
        if m:
            per_line.setdefault(tok.start[0], set()).update(
                x.strip() for x in m.group(1).split(","))
    return per_line, per_file


def _suppressed(finding, per_line, per_file):
    if "all" in per_file or finding.rule_id in per_file:
        return True
    # a comment on ANY line of the flagged statement counts: the natural spot
    # for a trailing `# graftlint: disable=` on a multi-line call is its last line
    last = max(finding.line, finding.end_line or finding.line)
    for line in range(finding.line, last + 1):
        ids = per_line.get(line, ())
        if "all" in ids or finding.rule_id in ids:
            return True
    return False


def _parse_error_finding(source, path, e):
    rule = ParseErrorRule()
    lines = source.splitlines()
    lineno = e.lineno or 1
    # a real code fingerprint: an empty one would make a baselined parse
    # error match EVERY future parse error in the file
    code = lines[lineno - 1].strip() if 0 < lineno <= len(lines) else ""
    return Finding(rule.rule_id, rule.severity, path, lineno,
                   (e.offset or 0) + 1, "syntax error: %s" % e.msg, code=code)


def _run_project_phase(contexts, project_rules):
    """Run each project rule once over the already-parsed corpus."""
    if not project_rules or not contexts:
        return []
    from petastorm_tpu.analysis.project import ProjectContext

    project = ProjectContext(contexts)
    findings = []
    for rule in project_rules:
        findings.extend(rule.check_project(project))
    return findings


def analyze_source(source, path="<string>", rules=None, project_rules=None):
    """Run rules over one source string. Returns (findings, suppressed_count).

    The project phase runs too, over a single-module corpus — so fixture
    strings exercise GL-C005/GL-C006 exactly like files on disk do."""
    rules = default_rules() if rules is None else rules
    project_rules = default_project_rules() if project_rules is None \
        else project_rules
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [_parse_error_finding(source, path, e)], 0
    ctx = FileContext(path, source, tree)
    per_line, per_file = _suppressions(source)
    findings, n_suppressed = [], 0
    all_findings = []
    for rule in rules:
        all_findings.extend(rule.check(tree, ctx))
    all_findings.extend(_run_project_phase([ctx], project_rules))
    for finding in all_findings:
        if _suppressed(finding, per_line, per_file):
            n_suppressed += 1
        else:
            findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule_id))
    return findings, n_suppressed


def iter_python_files(paths):
    """Expand files/directories into .py files (skips hidden dirs and __pycache__).

    A path that does not exist — or an explicit file that is not Python — raises
    instead of being silently skipped: a typo'd path in the CI lint step must
    fail the build (exit 2), not report '0 findings' forever. Overlapping path
    arguments (`lint dir/ dir/m.py`) are deduplicated — analyzing a file twice
    would double its findings and spuriously exhaust baseline counts."""
    seen = set()

    def emit(p):
        key = os.path.realpath(p)
        if key in seen:
            return None
        seen.add(key)
        return p

    for path in paths:
        if os.path.isfile(path):
            if not path.endswith(".py"):
                raise ValueError("not a Python file: %s" % path)
            p = emit(path)
            if p is not None:
                yield p
            continue
        if not os.path.isdir(path):
            raise FileNotFoundError("no such file or directory: %s" % path)
        for root, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames
                if not d.startswith(".") and d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    p = emit(os.path.join(root, fn))
                    if p is not None:
                        yield p


def analyze_paths(paths, rules=None, project_rules=None):
    """Run rules over files/directories. Returns (findings, suppressed_count).

    Each file is read and parsed ONCE; the resulting ``FileContext`` objects
    (with their cached walks and suppression maps) feed both the per-file
    phase and the whole-program project phase."""
    rules = default_rules() if rules is None else rules
    project_rules = default_project_rules() if project_rules is None \
        else project_rules
    findings, n_suppressed = [], 0
    contexts, suppression_maps = [], {}
    for path in iter_python_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            rule = ParseErrorRule()
            findings.append(Finding(rule.rule_id, rule.severity, path, 1, 1,
                                    "cannot read file: %s" % e))
            continue
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            findings.append(_parse_error_finding(source, path, e))
            continue
        ctx = FileContext(path, source, tree)
        contexts.append(ctx)
        per_line, per_file = _suppressions(source)
        suppression_maps[path] = (per_line, per_file)
        file_findings = []
        for rule in rules:
            file_findings.extend(rule.check(tree, ctx))
        file_findings.sort(key=lambda f: (f.line, f.col, f.rule_id))
        for finding in file_findings:
            if _suppressed(finding, per_line, per_file):
                n_suppressed += 1
            else:
                findings.append(finding)
    project_findings = _run_project_phase(contexts, project_rules)
    project_findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule_id))
    for finding in project_findings:
        per_line, per_file = suppression_maps.get(finding.path, ({}, set()))
        if _suppressed(finding, per_line, per_file):
            n_suppressed += 1
        else:
            findings.append(finding)
    return findings, n_suppressed
