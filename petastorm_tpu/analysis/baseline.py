"""Suppression baseline: known findings accepted with a justification.

The baseline file (``.graftlint-baseline.json``, checked in at the repo root)
lets the linter gate NEW findings while carrying a reviewed set of accepted
ones. Entries match on ``(rule, path, stripped source line)`` — not the line
number — so edits elsewhere in a file don't churn the baseline; ``count``
covers N identical lines (e.g. the same pattern in two branches).

Every entry carries a ``justification`` explaining why the finding is accepted
rather than fixed; ``petastorm-tpu-lint --write-baseline`` refreshes the file
(new entries get a TODO justification a reviewer must fill in).
"""
from __future__ import annotations

import json
import os


class Baseline:
    def __init__(self, entries=None, path=None):
        #: (rule, relpath, code) -> {"count": int, "justification": str}
        self.entries = entries or {}
        self.path = path

    # -- IO ----------------------------------------------------------------------------

    @classmethod
    def load(cls, path):
        with open(path, "r", encoding="utf-8") as f:
            payload = json.load(f)
        entries = {}
        for e in payload.get("entries", []):
            key = (e["rule"], e["path"], e["code"])
            entries[key] = {
                "count": int(e.get("count", 1)),
                "justification": e.get("justification", ""),
            }
        return cls(entries, path=path)

    @classmethod
    def find(cls, start_dir):
        """Locate ``.graftlint-baseline.json`` in ``start_dir`` or a parent."""
        d = os.path.abspath(start_dir)
        while True:
            candidate = os.path.join(d, ".graftlint-baseline.json")
            if os.path.isfile(candidate):
                return candidate
            parent = os.path.dirname(d)
            if parent == d:
                return None
            d = parent

    def save(self, path=None):
        path = path or self.path
        entries = []
        for (rule, relpath, code), meta in sorted(self.entries.items()):
            entries.append({
                "rule": rule,
                "path": relpath,
                "code": code,
                "count": meta["count"],
                "justification": meta["justification"],
            })
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "entries": entries}, f, indent=2)
            f.write("\n")

    # -- matching ----------------------------------------------------------------------

    def _relpath(self, finding_path):
        if self.path is None:
            return finding_path
        root = os.path.dirname(os.path.abspath(self.path))
        rel = os.path.relpath(os.path.abspath(finding_path), root)
        return rel.replace(os.sep, "/")

    def key_for(self, finding):
        return (finding.rule_id, self._relpath(finding.path), finding.code)

    def filter(self, findings):
        """Split findings into (new, baselined) honoring per-entry counts."""
        remaining = {k: v["count"] for k, v in self.entries.items()}
        new, baselined = [], []
        for f in findings:
            key = self.key_for(f)
            if remaining.get(key, 0) > 0:
                remaining[key] -= 1
                baselined.append(f)
            else:
                new.append(f)
        return new, baselined

    def stale_entries(self, findings):
        """Baseline entries with UNUSED capacity — fully fixed, or count:N
        entries where fewer than N occurrences remain. Partially-consumed
        entries matter: their leftover count would silently absorb the next NEW
        identical finding, so they must be reported for a --write-baseline
        refresh just like fully-fixed ones."""
        used = {}
        for f in findings:
            key = self.key_for(f)
            used[key] = used.get(key, 0) + 1
        return [key for key, meta in sorted(self.entries.items())
                if used.get(key, 0) < meta["count"]]

    @classmethod
    def from_findings(cls, findings, path, previous=None, analyzed_paths=None,
                      run_rules=None):
        """Build a baseline covering ``findings``; justifications carried over
        from ``previous`` when the entry already existed.

        ``analyzed_paths`` (relative paths, baseline-root convention) marks
        which files this run actually scanned, and ``run_rules`` which rule ids
        actually ran: previous entries for files OUTSIDE that set — or for
        rules excluded via --select/--ignore — are preserved verbatim. Running
        ``--write-baseline`` on a subset of the tree or of the rules must not
        prune the rest of the baseline ('not scanned' is not 'fixed')."""
        baseline = cls({}, path=path)
        for f in findings:
            if f.rule_id == "GL-X001":
                # a parse/read error is never an acceptable steady state — and
                # its fingerprint would match any future breakage of the file
                continue
            key = baseline.key_for(f)
            if key in baseline.entries:
                baseline.entries[key]["count"] += 1
            else:
                just = ""
                if previous is not None:
                    prev = previous.entries.get(key)
                    if prev:
                        just = prev["justification"]
                baseline.entries[key] = {
                    "count": 1,
                    "justification": just or "TODO: justify or fix",
                }
        if previous is not None:
            for key, meta in previous.entries.items():
                if key in baseline.entries:
                    continue
                outside_paths = analyzed_paths is not None \
                    and key[1] not in analyzed_paths
                outside_rules = run_rules is not None and key[0] not in run_rules
                if outside_paths or outside_rules:
                    baseline.entries[key] = dict(meta)
        return baseline
