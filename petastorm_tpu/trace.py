"""Chrome-trace span recording for the data pipeline.

The reference ships no tracing at all (SURVEY.md §6: "no spans, no per-stage timers
in the hot path"); ``PipelineStats`` already gives cheap per-stage TOTALS, and this
module adds the per-span view when you need to see *when* each stage ran: hand a
:class:`TraceRecorder` to ``DataLoader(trace=...)`` and every pipeline stage (reader
fetch, batch formation, device decode dispatch, H2D, queue waits — plus, on the
process pool's shared-memory wire, ``shm.acquire_wait`` spans from driver threads
starved for a free slab) records one
duration event per occurrence, tagged with its thread. Dump with :meth:`dump` and
load the file in ``chrome://tracing`` / Perfetto to see producer, transfer, and
consumer lanes and where the bubbles are.

Overhead when enabled is one ``perf_counter`` pair (already paid for stats) plus an
appended tuple per span — no formatting until :meth:`dump`; disabled (``trace=None``,
the default) it costs one ``is None`` check per span site.

Cross-process merge (ISSUE 3): pool children record spans around each work item
(:mod:`petastorm_tpu._child_worker`) and piggyback them on the result header;
the driver thread folds them in via :meth:`add_child`, clock-aligned through
each child's wall/perf anchor pair (same host, shared wall clock), so one dump
shows driver threads AND worker processes on distinct pid lanes.

    from petastorm_tpu.trace import TraceRecorder

    tracer = TraceRecorder()
    with DataLoader(reader, 256, trace=tracer) as loader:
        for batch in loader:
            with tracer.span("train.step"):
                step(batch)
    tracer.dump("pipeline_trace.json")
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time


class TraceRecorder:
    """Thread-safe duration-event recorder in Chrome trace-event format.

    ``max_events`` bounds memory on long runs (a span is one small tuple, but a
    multi-hour run at hundreds of batches/s would otherwise grow without limit):
    once full, the OLDEST spans are dropped — the dump shows the most recent
    window, which is the one being debugged. ``None`` disables the bound."""

    def __init__(self, max_events=1_000_000):
        from collections import deque

        # (name, lane key (tname, tid), t0_s, dur_s, pid-or-None (None = local))
        self._events = deque(maxlen=max_events)
        #: provenance flow points (ISSUE 10): (flow_id, lane, pid, t, name,
        #: terminate) — rendered as Perfetto flow events ("s"/"t"/"f") linking
        #: one item's spans across pid lanes in the dump
        self._flows = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self._origin = time.perf_counter()
        #: wall-clock instant matching ``_origin`` — the cross-process alignment
        #: anchor (children ship their own (wall, perf) pair; same host, so the
        #: shared wall clock maps child perf_counter values onto this timeline)
        self._wall_origin = time.time()

    def add(self, name, t0, dur):
        """Record one span: ``t0`` from ``time.perf_counter()``, ``dur`` seconds."""
        t = threading.current_thread()
        # keyed by (name, ident): two live threads may SHARE a name (e.g. a train
        # and an eval loader both run a "ptpu-loader" producer) and collapsing them
        # onto one trace lane would render bogus nested slices
        with self._lock:
            self._events.append((name, (t.name, t.ident), t0, dur, None))

    def add_child(self, pid, spans, wall_anchor, perf_anchor, lane=None):
        """Merge spans recorded in a pool child process onto a pid-tagged lane.

        ``spans`` is ``[(name, t0, dur), ...]`` with ``t0`` from the CHILD's
        ``perf_counter``; ``(wall_anchor, perf_anchor)`` is a pair the child
        sampled together, so each span start maps to this recorder's timeline as
        ``wall_anchor + (t0 - perf_anchor)`` on the shared wall clock. Alignment
        error is the wall-clock sampling jitter (microseconds on one host)."""
        if not spans:
            return
        lane = lane or ("ptpu-child-%d" % pid)
        base = (wall_anchor - self._wall_origin) - perf_anchor + self._origin
        with self._lock:
            for name, t0, dur in spans:
                self._events.append((name, (lane, pid), t0 + base, dur, pid))

    def add_flow_point(self, flow_id, lane, pid, t, name="item",
                       terminate=False):
        """Record one point of a Perfetto flow (ISSUE 10: the provenance
        plane's item linkage). ``t`` is a value from THIS recorder's timeline
        (``perf_counter``; child spans are pre-aligned by the provenance
        merge); points sharing ``flow_id`` render as one arrow chain across
        the ``(pid, lane)`` tracks. ``terminate=True`` marks the chain's
        explicit end (the batch delivery point)."""
        with self._lock:
            self._flows.append((int(flow_id), lane, int(pid), t, name,
                                bool(terminate)))

    @contextlib.contextmanager
    def span(self, name):
        """Context manager recording the enclosed block as one span."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, t0, time.perf_counter() - t0)

    def __len__(self):
        with self._lock:
            return len(self._events)

    def events(self):
        """Snapshot of recorded spans as dicts (name/thread/pid/start_s/
        duration_s); ``pid`` is this process for locally recorded spans."""
        with self._lock:
            evs = list(self._events)
        local = os.getpid()
        return [{"name": n, "thread": t[0], "pid": p if p is not None else local,
                 "start_s": t0 - self._origin, "duration_s": d}
                for n, t, t0, d, p in evs]

    def dump(self, path):
        """Write ``chrome://tracing`` / Perfetto JSON (trace-event format).

        Lanes are per (process, thread): locally recorded spans render under
        this process's pid, child spans under THEIR pid with a ``process_name``
        metadata row per child process — one timeline, distinct pid lanes."""
        with self._lock:
            evs = list(self._events)
            flows = list(self._flows)
        local_pid = os.getpid()
        lanes = {}  # (pid, lane key) -> (tid, lane display name)
        for _n, tkey, _t0, _d, p in evs:
            key = (p if p is not None else local_pid, tkey)
            if key not in lanes:
                lanes[key] = tkey[0]
        for _fid, lane, fpid, _t, _n, _term in flows:
            # flow points land on (lane, pid)-keyed tracks like child spans do;
            # a point naming a lane no slice lives on still gets its own track
            key = (fpid, (lane, fpid))
            if key not in lanes:
                lanes[key] = lane
        trace_events = []
        tids = {}
        child_pids = sorted({p for _n, _t, _t0, _d, p in evs if p is not None
                             and p != local_pid}
                            | {fpid for _fid, _l, fpid, _t, _n, _term in flows
                               if fpid != local_pid})
        if child_pids:  # pid lanes only exist on merged multi-process dumps
            for pid in [local_pid] + child_pids:
                trace_events.append({
                    "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
                    "args": {"name": "ptpu-driver" if pid == local_pid
                             else "ptpu-pool-child-%d" % pid}})
        for key in sorted(lanes, key=str):
            tid = tids[key] = len(tids) + 1
            trace_events.append({  # thread-name metadata row
                "ph": "M", "pid": key[0], "tid": tid, "name": "thread_name",
                "args": {"name": lanes[key]}})
        for name, tkey, t0, dur, p in evs:
            pid = p if p is not None else local_pid
            trace_events.append({
                "ph": "X", "pid": pid, "tid": tids[(pid, tkey)], "name": name,
                "ts": (t0 - self._origin) * 1e6, "dur": dur * 1e6, "cat": "pipeline"})
        # provenance flows (ISSUE 10): chain each flow id's points in time
        # order — "s" start, "t" steps, "f" finish — so Perfetto draws arrows
        # linking one item's spans across pid lanes
        by_flow = {}
        for fid, lane, fpid, t, name, term in flows:
            by_flow.setdefault(fid, []).append((t, lane, fpid, name, term))
        for fid, points in by_flow.items():
            points.sort(key=lambda p: p[0])
            for i, (t, lane, fpid, name, term) in enumerate(points):
                if i == 0:
                    ph = "s"
                elif i == len(points) - 1 or term:
                    ph = "f"
                else:
                    ph = "t"
                ev = {"ph": ph, "id": fid, "pid": fpid,
                      "tid": tids[(fpid, (lane, fpid))], "name": name,
                      "ts": (t - self._origin) * 1e6, "cat": "prov"}
                if ph == "f":
                    ev["bp"] = "e"  # bind to the enclosing slice
                trace_events.append(ev)
                if ph == "f":
                    break
        with open(path, "w") as f:
            json.dump({"traceEvents": trace_events,
                       "displayTimeUnit": "ms"}, f)
        return path
