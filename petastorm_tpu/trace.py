"""Chrome-trace span recording for the data pipeline.

The reference ships no tracing at all (SURVEY.md §6: "no spans, no per-stage timers
in the hot path"); ``PipelineStats`` already gives cheap per-stage TOTALS, and this
module adds the per-span view when you need to see *when* each stage ran: hand a
:class:`TraceRecorder` to ``DataLoader(trace=...)`` and every pipeline stage (reader
fetch, batch formation, device decode dispatch, H2D, queue waits — plus, on the
process pool's shared-memory wire, ``shm.acquire_wait`` spans from driver threads
starved for a free slab) records one
duration event per occurrence, tagged with its thread. Dump with :meth:`dump` and
load the file in ``chrome://tracing`` / Perfetto to see producer, transfer, and
consumer lanes and where the bubbles are.

Overhead when enabled is one ``perf_counter`` pair (already paid for stats) plus an
appended tuple per span — no formatting until :meth:`dump`; disabled (``trace=None``,
the default) it costs one ``is None`` check per span site.

    from petastorm_tpu.trace import TraceRecorder

    tracer = TraceRecorder()
    with DataLoader(reader, 256, trace=tracer) as loader:
        for batch in loader:
            with tracer.span("train.step"):
                step(batch)
    tracer.dump("pipeline_trace.json")
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time


class TraceRecorder:
    """Thread-safe duration-event recorder in Chrome trace-event format.

    ``max_events`` bounds memory on long runs (a span is one small tuple, but a
    multi-hour run at hundreds of batches/s would otherwise grow without limit):
    once full, the OLDEST spans are dropped — the dump shows the most recent
    window, which is the one being debugged. ``None`` disables the bound."""

    def __init__(self, max_events=1_000_000):
        from collections import deque

        self._events = deque(maxlen=max_events)  # (name, (tname, tid), t0_s, dur_s)
        self._lock = threading.Lock()
        self._origin = time.perf_counter()

    def add(self, name, t0, dur):
        """Record one span: ``t0`` from ``time.perf_counter()``, ``dur`` seconds."""
        t = threading.current_thread()
        # keyed by (name, ident): two live threads may SHARE a name (e.g. a train
        # and an eval loader both run a "ptpu-loader" producer) and collapsing them
        # onto one trace lane would render bogus nested slices
        with self._lock:
            self._events.append((name, (t.name, t.ident), t0, dur))

    @contextlib.contextmanager
    def span(self, name):
        """Context manager recording the enclosed block as one span."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, t0, time.perf_counter() - t0)

    def __len__(self):
        with self._lock:
            return len(self._events)

    def events(self):
        """Snapshot of recorded spans as dicts (name/thread/start_s/duration_s)."""
        with self._lock:
            evs = list(self._events)
        return [{"name": n, "thread": t[0], "start_s": t0 - self._origin,
                 "duration_s": d} for n, t, t0, d in evs]

    def dump(self, path):
        """Write ``chrome://tracing`` / Perfetto JSON (trace-event format)."""
        with self._lock:
            evs = list(self._events)
        pid = os.getpid()
        tids = {}
        trace_events = []
        for tkey in sorted({t for _n, t, _t0, _d in evs}, key=str):
            tid = tids[tkey] = len(tids) + 1
            trace_events.append({  # thread-name metadata row
                "ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
                "args": {"name": tkey[0]}})
        for name, tkey, t0, dur in evs:
            trace_events.append({
                "ph": "X", "pid": pid, "tid": tids[tkey], "name": name,
                "ts": (t0 - self._origin) * 1e6, "dur": dur * 1e6, "cat": "pipeline"})
        with open(path, "w") as f:
            json.dump({"traceEvents": trace_events,
                       "displayTimeUnit": "ms"}, f)
        return path
