"""Process-wide metrics registry: counters, gauges, log-bucketed histograms.

The survey's complaint about the reference ("no spans, no per-stage timers in
the hot path") was first answered piecemeal: ``PipelineStats`` totals on the
loader, ``Reader.wire_stats()`` for the shm wire, ``SlabRing.stats()`` gauges.
This module is the one coherent layer those one-offs migrate onto: a named
metric **family** is a metric name plus a label set (Prometheus data model), a
**registry** owns every family in the process, and exporters/analyzers consume
one ``snapshot()`` instead of knowing each subsystem's ad-hoc dict.

Design constraints, in order:

- **Near-zero disabled path.** Nothing in the hot loops touches the registry
  unless observability was requested; instrumented sites follow ``trace.py``'s
  contract — one ``is None`` check when disabled. Components therefore take a
  pre-resolved metric object (or a tiny struct of them), never a registry
  lookup per event.
- **Cheap enabled path.** ``Counter.inc``/``Histogram.observe`` are one lock
  acquire plus integer arithmetic (~0.2-0.4 µs; measured numbers in
  docs/observability.md). Histograms are log-bucketed — an observation maps to
  a bucket index via ``math.frexp`` (no ``log`` call, no stored samples), so
  p50/p90/p99 come from ~dozens of integers however long the run.
- **Pull, don't push, for existing gauges.** Subsystems that already keep cheap
  totals (``PipelineStats``, the slab ring) are exported through registered
  *collectors* — callables polled at snapshot time — so their hot paths did not
  change at all.

``default_registry()`` returns the process-wide registry (created on first
use); tests build private ``MetricsRegistry()`` instances instead.
"""
from __future__ import annotations

import math
import threading

#: log-bucket resolution: buckets per power of two (2**(1/4) ≈ 19% wide — tight
#: enough that a p99 read from a bucket upper bound is within ~19% of the true
#: sample, coarse enough that a microseconds-to-minutes range is ~80 buckets)
_BUCKETS_PER_OCTAVE = 4


class _Metric:
    """Shared identity/labels plumbing; subclasses hold the value under _lock."""

    kind = "untyped"

    def __init__(self, name, labels=(), help=""):
        self.name = name
        self.labels = tuple(labels)  # sorted (key, value) pairs
        self.help = help
        self._lock = threading.Lock()

    def label_suffix(self):
        if not self.labels:
            return ""
        return "{%s}" % ",".join('%s="%s"' % (k, v) for k, v in self.labels)

    @property
    def full_name(self):
        """``name{k="v",...}`` — the flat snapshot/JSONL key."""
        return self.name + self.label_suffix()


class Counter(_Metric):
    """Monotonic count (events, bytes, degradations)."""

    kind = "counter"

    def __init__(self, name, labels=(), help=""):
        super().__init__(name, labels, help)
        self._value = 0

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge(_Metric):
    """Point-in-time level (queue depth, slabs in flight)."""

    kind = "gauge"

    def __init__(self, name, labels=(), help=""):
        super().__init__(name, labels, help)
        self._value = 0.0

    def set(self, v):
        with self._lock:
            self._value = v

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    @property
    def value(self):
        with self._lock:
            return self._value


def _bucket_index(v):
    """Log-bucket index of a positive value: ``i`` such that the bucket upper
    bound is ``2**(i / _BUCKETS_PER_OCTAVE)``. frexp-based — no transcendental
    call on the observe path."""
    # frexp: v = m * 2**e with m in [0.5, 1) -> the index is
    # ceil(log2(v) * S) = S*(e-1) + ceil(S * log2(2m)); the sub-octave step is
    # resolved by comparing m against S precomputed mantissa thresholds instead
    # of calling a transcendental on the observe path.
    m, e = math.frexp(v)
    octave_base = (e - 1) * _BUCKETS_PER_OCTAVE
    if m == 0.5:  # exact power of two sits on its own bucket boundary
        return octave_base
    for step, bound in enumerate(_MANTISSA_BOUNDS, start=1):
        if m <= bound:
            return octave_base + step
    return octave_base + _BUCKETS_PER_OCTAVE  # unreachable: last bound is 1.0


#: mantissa thresholds for sub-octave steps: 0.5 * 2**(k/4), k=1..4
_MANTISSA_BOUNDS = tuple(0.5 * 2 ** (k / _BUCKETS_PER_OCTAVE)
                         for k in range(1, _BUCKETS_PER_OCTAVE + 1))


def bucket_upper_bound(index):
    """Upper bound of bucket ``index`` (seconds/bytes/whatever was observed)."""
    return 2.0 ** (index / _BUCKETS_PER_OCTAVE)


class Histogram(_Metric):
    """Log-bucketed distribution: percentiles without storing samples.

    ``observe(v)`` increments one bucket counter (``{index: count}`` dict);
    ``percentile(q)`` walks the cumulative counts and returns the matched
    bucket's upper bound — an over-estimate by at most one bucket width (~19%),
    the right bias for latency percentiles. Zero/negative observations land in
    a dedicated underflow bucket reported as 0.0.
    """

    kind = "histogram"

    def __init__(self, name, labels=(), help=""):
        super().__init__(name, labels, help)
        self._buckets = {}  # bucket index -> count
        self._zero = 0      # observations <= 0
        self._count = 0
        self._sum = 0.0
        self._max = 0.0

    def observe(self, v):
        with self._lock:
            self._count += 1
            self._sum += v
            if v <= 0.0:
                self._zero += 1
                return
            if v > self._max:
                self._max = v
            idx = _bucket_index(v)
            self._buckets[idx] = self._buckets.get(idx, 0) + 1

    def reset(self):
        """Zero the distribution (benchmark windows re-anchor percentiles to the
        measured window, like ``PipelineStats.reset()`` re-anchors the totals)."""
        with self._lock:
            self._buckets = {}
            self._zero = 0
            self._count = 0
            self._sum = 0.0
            self._max = 0.0

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def percentile(self, q):
        """Upper bound of the bucket holding the ``q``-quantile (0 < q <= 1);
        0.0 for an empty histogram."""
        with self._lock:
            count = self._count
            if count == 0:
                return 0.0
            target = q * count
            cum = self._zero
            if cum >= target:
                return 0.0
            for idx in sorted(self._buckets):
                cum += self._buckets[idx]
                if cum >= target:
                    return min(bucket_upper_bound(idx), self._max)
            return self._max

    def snapshot(self):
        """Summary dict: count/sum/mean/max + p50/p90/p99 (export + CLI shape)."""
        with self._lock:
            count, total, mx = self._count, self._sum, self._max
        return {
            "count": count,
            "sum": round(total, 6),
            "mean": round(total / count, 6) if count else 0.0,
            "max": round(mx, 6),
            "p50": round(self.percentile(0.50), 6),
            "p90": round(self.percentile(0.90), 6),
            "p99": round(self.percentile(0.99), 6),
        }

    def cumulative_buckets(self):
        """[(upper_bound, cumulative_count)] ascending — Prometheus export shape
        (the +Inf bucket is the caller's job: it equals ``count``)."""
        return self.export_state()[0]

    def export_state(self):
        """``(cumulative_buckets, count, sum)`` read under ONE lock acquisition:
        the Prometheus invariant ``le="+Inf" bucket == _count`` must hold even
        while another thread observes between exposition lines."""
        with self._lock:
            items = sorted(self._buckets.items())
            cum = self._zero
            out = []
            if self._zero:
                out.append((0.0, cum))
            for idx, n in items:
                cum += n
                out.append((bucket_upper_bound(idx), cum))
            return out, self._count, self._sum


_METRIC_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Owns every metric family in the process; snapshot/export entry point.

    A family is get-or-created by ``counter()``/``gauge()``/``histogram()``
    (idempotent — same name+labels returns the same object, so callers resolve
    once and keep the reference off the hot path). ``register_collector``
    attaches a pull-mode source: a callable returning ``{suffix: number}``
    polled at snapshot time and exported as gauges named
    ``ptpu_<prefix>_<suffix>`` — the migration path for ``PipelineStats``,
    ``Reader.wire_stats()`` and the slab-ring gauges, whose hot paths stay
    exactly as cheap as before.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}     # (name, labels tuple) -> metric
        self._families = {}    # name -> (kind, help)
        self._collectors = {}  # handle (int) -> (prefix, fn)
        self._next_handle = 0
        #: lazily-built windowed time-series store (ISSUE 12); None until the
        #: first timeline_store()/sample_timelines() call, so registries that
        #: never asked for the temporal plane pay nothing
        self._timeline_store = None

    # -- family construction ------------------------------------------------------------

    def _get_or_create(self, kind, name, help, labels):
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            metric = self._metrics.get(key)
            if metric is not None:
                if metric.kind != kind:
                    raise ValueError(
                        "metric family %r already registered as %s, not %s"
                        % (name, metric.kind, kind))
                return metric
            fam = self._families.get(name)
            if fam is not None and fam[0] != kind:
                raise ValueError(
                    "metric family %r already registered as %s, not %s"
                    % (name, fam[0], kind))
            metric = _METRIC_TYPES[kind](name, key[1], help or (fam[1] if fam else ""))
            self._metrics[key] = metric
            if fam is None:
                self._families[name] = (kind, help)
            return metric

    def counter(self, name, help="", **labels):
        return self._get_or_create("counter", name, help, labels)

    def gauge(self, name, help="", **labels):
        return self._get_or_create("gauge", name, help, labels)

    def histogram(self, name, help="", **labels):
        return self._get_or_create("histogram", name, help, labels)

    # -- pull-mode collectors -----------------------------------------------------------

    def register_collector(self, prefix, fn):
        """Register ``fn() -> {suffix: number}`` polled at snapshot time; values
        export as gauges ``ptpu_<prefix>_<suffix>``. Returns a handle for
        :meth:`unregister_collector` (loaders unregister at ``__exit__`` so a
        dead pipeline stops contributing stale families)."""
        with self._lock:
            handle = self._next_handle
            self._next_handle += 1
            self._collectors[handle] = (prefix, fn)
        return handle

    def unregister_collector(self, handle):
        """Accepts one handle or an iterable of handles (``Reader
        .register_metrics`` returns a list since it registers wire AND io
        collectors)."""
        handles = handle if isinstance(handle, (list, tuple, set)) \
            else (handle,)
        with self._lock:
            for h in handles:
                self._collectors.pop(h, None)

    def _collect(self):
        with self._lock:
            collectors = list(self._collectors.values())
        out = {}
        for prefix, fn in collectors:
            try:
                polled = fn()
            except Exception:  # noqa: BLE001 — a dead source must not kill export
                continue
            for suffix, value in (polled or {}).items():
                if isinstance(value, dict):
                    # tenant-keyed breakdowns (ISSUE 18: the arena's
                    # index-derived ``arena_tenant_bytes``) flatten into
                    # tenant-labeled series — export and timelines only
                    # speak scalars
                    for tenant, v in value.items():
                        if isinstance(v, (int, float)):
                            out['ptpu_%s_%s{tenant="%s"}'
                                % (prefix, suffix, tenant)] = v
                    continue
                out["ptpu_%s_%s" % (prefix, suffix)] = value
        return out

    # -- windowed time-series (ISSUE 12) ------------------------------------------------

    def _timeline_sources(self):
        """Raw per-series reads for the timeline sampler
        (:mod:`petastorm_tpu.obs.timeseries`): ``[(full_name, kind, payload)]``
        where payload is the scalar value for counters/gauges and
        ``export_state()`` for histograms. Collector values ride along typed
        by suffix (``*_total`` = counter semantics, everything else a level) —
        their sources keep cumulative floats (``read_s``, ``rows``) that the
        sampler differences either way."""
        with self._lock:
            metrics = list(self._metrics.values())
        out = []
        for m in metrics:
            if m.kind == "histogram":
                out.append((m.full_name, "histogram", m.export_state()))
            else:
                out.append((m.full_name, m.kind, m.value))
        for name, value in self._collect().items():
            if not isinstance(value, (int, float)):
                continue  # non-scalar collector payloads never window
            kind = "counter" if name.endswith("_total") else "gauge"
            out.append((name, kind, float(value)))
        return out

    def timeline_store(self, **kwargs):
        """The registry's :class:`~petastorm_tpu.obs.timeseries.TimelineStore`
        (created on first use; ``kwargs`` — ``max_points``/``max_series`` —
        apply only at creation). Sampling happens on whatever cadence calls
        :meth:`sample_timelines` (the Reporter thread, normally) — never on an
        instrumented hot path."""
        with self._lock:
            if self._timeline_store is None:
                from petastorm_tpu.obs.timeseries import TimelineStore

                self._timeline_store = TimelineStore(self, **kwargs)
            return self._timeline_store

    def sample_timelines(self):
        """Sample every series into the timeline rings (one pass, one lock per
        metric); returns the window dict. The Reporter calls this per flush."""
        return self.timeline_store().sample()

    def timeline(self, name):
        """Windowed points of one series (full snapshot name, labels included)
        — ``[]`` until the store has sampled it. Counters read back as
        delta/rate points, histograms as per-window p50/p99."""
        store = self._timeline_store
        return store.points(name) if store is not None else []

    # -- output -------------------------------------------------------------------------

    def snapshot(self):
        """Flat ``{full_name: value}`` dict — numbers for counters/gauges and
        collector values, summary dicts (count/sum/percentiles) for histograms."""
        with self._lock:
            metrics = list(self._metrics.values())
        out = {}
        for m in metrics:
            out[m.full_name] = m.snapshot() if m.kind == "histogram" else m.value
        out.update(self._collect())
        return out

    def to_prometheus(self):
        """Prometheus text exposition format (one string, trailing newline)."""
        with self._lock:
            metrics = list(self._metrics.values())
            families = dict(self._families)
        by_family = {}
        for m in metrics:
            by_family.setdefault(m.name, []).append(m)
        lines = []
        for name in sorted(by_family):
            kind, help = families.get(name, ("gauge", ""))
            if help:
                lines.append("# HELP %s %s" % (name, help))
            lines.append("# TYPE %s %s" % (name, kind))
            for m in sorted(by_family[name], key=lambda m: m.labels):
                if m.kind == "histogram":
                    base = list(m.labels)
                    buckets, count, total = m.export_state()  # one consistent read
                    for bound, cum in buckets:
                        le = _labels_text(base + [("le", "%.6g" % bound)])
                        lines.append("%s_bucket%s %d" % (name, le, cum))
                    le = _labels_text(base + [("le", "+Inf")])
                    lines.append("%s_bucket%s %d" % (name, le, count))
                    lines.append("%s_sum%s %.9g" % (name, m.label_suffix(), total))
                    lines.append("%s_count%s %d" % (name, m.label_suffix(), count))
                else:
                    lines.append("%s%s %.9g" % (name, m.label_suffix(), m.value))
        for full_name, value in sorted(self._collect().items()):
            lines.append("# TYPE %s gauge" % full_name)
            lines.append("%s %.9g" % (full_name, float(value)))
        return "\n".join(lines) + "\n"


def _labels_text(pairs):
    return "{%s}" % ",".join('%s="%s"' % (k, v) for k, v in pairs)


_default_lock = threading.Lock()
_default = None


def default_registry():
    """The process-wide registry (created on first use). Degradation counters
    (:mod:`petastorm_tpu.obs.log`) and anything wired with ``metrics=True``
    land here, so one exporter sees the whole process."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default
