"""Pipeline health: heartbeats, a backpressure-aware stall watchdog, flight dumps.

ISSUE 3 made the pipeline *observable* (metrics, traces, the bottleneck
analyzer) and ISSUE 4 made it deeply concurrent (IO threads, work-stealing
claims, process-pool prefetch) — which moved the production failure mode from
"slow" to "silently hung or limping". This module is the ACTIVE monitoring
layer: every long-lived actor stamps a :class:`Heartbeat`, a daemon
:class:`StallWatchdog` compares heartbeat ages against per-role thresholds,
and a detected stall produces one structured **flight record** (driver thread
stacks via ``sys._current_frames``, child-process stacks via the executor's
SIGUSR1/faulthandler hook, queue depths, metrics, degradations, and the
:class:`~petastorm_tpu.obs.flight.FlightRecorder` ring of recent events).

Backpressure awareness is the load-bearing design point: a producer blocked on
a FULL host queue is *waiting on downstream*, not stalled — so every blocking
site stamps a ``wait:*`` state before parking, and the watchdog only calls an
actor stalled when its age exceeds the threshold **in a busy state**. A slow
consumer therefore produces zero false positives while a hung decode (busy
state ``working``, age growing) trips within one poll interval of its
threshold.

Cost contract (same as ``trace.py`` and the ISSUE-3 stage histograms):
disabled — the default — is one ``is None`` check per site; enabled is one or
two attribute stores per pipeline *stage* per batch (a ``Heartbeat.beat`` is
two plain attribute writes, no lock), measured ≤1% on
``petastorm-tpu-bench --smoke`` (docs/observability.md).

Escalation policy (:class:`HealthOptions.escalation`): ``"warn"`` logs +
counts (``ptpu_degradations_total{cause="stall_detected"}``), ``"flight"``
(default) additionally writes the flight record, ``"raise"`` additionally
delivers a :class:`petastorm_tpu.errors.StallError` to the consumer so a
training loop fails fast instead of hanging a TPU slice.
"""
from __future__ import annotations

import logging
import os
import sys
import threading
import time
import traceback

from petastorm_tpu.errors import StallError
from petastorm_tpu.obs.flight import (
    FlightRecorder,
    activate,
    deactivate,
    write_flight_record,
)

logger = logging.getLogger("petastorm_tpu.obs")


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def health_enabled_by_env():
    """True when ``PTPU_HEALTH`` requests monitoring without code changes."""
    return os.environ.get("PTPU_HEALTH", "") not in ("", "0", "false", "no")


class HealthOptions:
    """Configuration for one :class:`HealthMonitor`.

    Parameters
    ----------
    stall_threshold_s : float
        Default busy-state heartbeat age past which an actor is stalled
        (``PTPU_HEALTH_THRESHOLD_S`` overrides). Generous by default: a real
        row-group read + decode against a cold object store can take tens of
        seconds without anything being wrong.
    thresholds : dict, optional
        Per-role overrides, e.g. ``{"worker": 30.0, "io": 60.0}`` — roles are
        ``producer``, ``transfer``, ``worker``, ``io``, ``child``.
    poll_interval_s : float
        Watchdog wake cadence; detection latency is ``threshold + poll``.
    escalation : {"warn", "flight", "heal", "raise"}
        Cumulative: ``warn`` logs+counts, ``flight`` also dumps the flight
        record, ``heal`` additionally asks registered healers to recover the
        stalled actors in place (the process pool's healer kills the hung
        child so the elastic-respawn machinery re-dispatches its item —
        ISSUE 7) and only delivers :class:`StallError` when no healer could
        act (no registered healer for the actor, or the respawn budget is
        exhausted), ``raise`` always delivers :class:`StallError` to the
        consumer.
    flight_path : str
        Where the flight record lands (most recent record wins; the path is
        stable so dashboards/CI can poll it). Default
        ``ptpu_flight_<pid>.json`` in the working directory.
    max_events : int
        Flight-recorder ring size.
    """

    __slots__ = ("stall_threshold_s", "thresholds", "poll_interval_s",
                 "escalation", "flight_path", "max_events")

    def __init__(self, stall_threshold_s=None, thresholds=None,
                 poll_interval_s=None, escalation="flight", flight_path=None,
                 max_events=2048):
        if escalation not in ("warn", "flight", "heal", "raise"):
            raise ValueError(
                "escalation must be warn|flight|heal|raise, got %r"
                % (escalation,))
        self.stall_threshold_s = float(
            stall_threshold_s if stall_threshold_s is not None
            else _env_float("PTPU_HEALTH_THRESHOLD_S", 120.0))
        self.thresholds = dict(thresholds or {})
        self.poll_interval_s = float(
            poll_interval_s if poll_interval_s is not None
            else _env_float("PTPU_HEALTH_POLL_S", 1.0))
        self.escalation = escalation
        self.flight_path = flight_path or os.path.join(
            os.environ.get("PTPU_HEALTH_DIR", "") or ".",
            "ptpu_flight_%d.json" % os.getpid())
        self.max_events = int(max_events)

    def threshold_for(self, role):
        return float(self.thresholds.get(role, self.stall_threshold_s))


class Heartbeat:
    """One actor's liveness stamp: ``(state, last-beat monotonic time)``.

    ``beat(state)`` is two attribute stores — no lock, by design: each slot is
    written by ONE actor thread and read by the watchdog. ``last`` is stored
    BEFORE ``state`` so a torn read lands on the safe side: at a wait→busy
    transition (where ``last`` may be arbitrarily stale after a long
    legitimate block) the watchdog can only ever pair the busy state with the
    FRESH timestamp — the other interleaving shows the old wait state, which
    is exempt. The reverse order could pair busy with the stale stamp and
    deliver a spurious ``StallError`` under ``escalation="raise"``. States:
    plain strings are BUSY (``working``, ``read``, ``decode``, ...); a
    ``wait:*`` prefix marks a legitimate block (backpressure, idle claim
    polling) the watchdog must not call a stall; ``done`` retires the actor.
    """

    __slots__ = ("name", "role", "threshold_s", "last", "state", "_reported")

    def __init__(self, name, role, threshold_s):
        self.name = name
        self.role = role
        self.threshold_s = threshold_s
        self.last = time.monotonic()
        self.state = "init"
        self._reported = False

    def beat(self, state="working"):
        self.last = time.monotonic()  # before state: see the torn-read note
        self.state = state
        self._reported = False

    def wait(self, what):
        """Stamp a legitimate blocking state (backpressure / idle)."""
        self.beat("wait:" + what)

    def done(self):
        self.beat("done")

    def age(self, now=None):
        return (now if now is not None else time.monotonic()) - self.last

    @property
    def waiting(self):
        return self.state == "done" or self.state.startswith("wait:")

    def describe(self, now=None):
        return {"actor": self.name, "role": self.role, "state": self.state,
                "age_s": round(self.age(now), 3),
                "threshold_s": self.threshold_s}


def _sanitize(name):
    """Metric-suffix-safe actor name (collector keys become Prometheus names)."""
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def driver_thread_stacks():
    """``{thread-name-ident: formatted stack}`` for every live thread in THIS
    process (``sys._current_frames`` — the same evidence ``faulthandler``
    prints, but structured and capturable without a signal)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        label = "%s-%d" % (names.get(tid, "thread"), tid)
        out[label] = "".join(traceback.format_stack(frame))
    return out


class HealthMonitor:
    """Registry of heartbeats + the flight recorder + the stall watchdog.

    One monitor watches one pipeline (a ``DataLoader`` builds and owns one via
    ``health=``; standalone readers/executors can share one through their
    ``set_health``). ``start()`` arms the watchdog daemon and activates the
    flight recorder for degradation mirroring; ``stop()`` (or the context
    manager) retires both. All registration APIs are thread-safe; the beat
    path itself is lock-free (see :class:`Heartbeat`).
    """

    def __init__(self, options=None, registry=None):
        self.options = options if options is not None else HealthOptions()
        self.flight = FlightRecorder(self.options.max_events)
        self._lock = threading.Lock()
        self._hbs = {}                # name -> Heartbeat
        self._stack_providers = {}    # handle -> fn() -> {label: stack text}
        self._contexts = {}           # handle -> (name, fn() -> dict)
        self._stall_callbacks = {}    # handle -> fn(StallError)
        self._healers = {}            # handle -> fn(stalled) -> healed names
        self._heals = 0
        self._next_handle = 0
        self._stalls = 0
        self._last_record_path = None
        self._watchdog = None
        self._stop_event = threading.Event()
        self._registry = registry
        self._worker_hists = {}       # key -> Histogram

    # -- heartbeat registry -------------------------------------------------------------

    def register(self, name, role, threshold_s=None):
        """Get-or-create the heartbeat for ``name`` (idempotent — actors
        re-registering across iterations reuse their slot, re-stamped)."""
        with self._lock:
            hb = self._hbs.get(name)
            if hb is None:
                hb = self._hbs[name] = Heartbeat(
                    name, role,
                    threshold_s if threshold_s is not None
                    else self.options.threshold_for(role))
            else:
                hb.beat("init")
            return hb

    def unregister(self, name):
        with self._lock:
            self._hbs.pop(name, None)

    def unregister_prefix(self, prefix):
        """Retire every actor and worker-latency slot under ``prefix + "/"``:
        a scoped pipeline detaching from a shared monitor. Without this each
        closed loader generation would leave its ``pipeN/*`` heartbeats
        registered forever — exported as ever-aging gauges, listed in every
        future flight record, and growing the monitor unboundedly."""
        cut = prefix + "/"
        with self._lock:
            for name in [n for n in self._hbs
                         if isinstance(n, str) and n.startswith(cut)]:
                del self._hbs[name]
            for key in [k for k in self._worker_hists
                        if isinstance(k, str) and k.startswith(cut)]:
                del self._worker_hists[key]

    def heartbeats(self, now=None):
        """Point-in-time description of every registered actor."""
        now = now if now is not None else time.monotonic()
        with self._lock:
            hbs = list(self._hbs.values())
        return [hb.describe(now) for hb in hbs]

    # -- per-worker latency (straggler detection) ---------------------------------------

    def observe_worker(self, key, dur):
        """Record one work-item latency for worker ``key`` (executor index) —
        the ``ptpu_worker_item_seconds{worker=...}`` histograms feeding the
        analyzer's ``straggler`` verdict."""
        hist = self._worker_hists.get(key)
        if hist is None:
            from petastorm_tpu.obs.metrics import default_registry

            reg = self._registry if self._registry is not None \
                else default_registry()
            hist = reg.histogram(
                "ptpu_worker_item_seconds",
                help="per-worker work-item latency (straggler detection)",
                worker=str(key))
            with self._lock:
                self._worker_hists.setdefault(key, hist)
        hist.observe(dur)

    def set_registry(self, registry):
        """Route the per-worker latency histograms onto ``registry`` (the
        loader wires its ``metrics=`` registry here so worker latencies export
        beside the stage histograms). No-op once observations exist — moving a
        live family would split its history across registries."""
        with self._lock:
            if not self._worker_hists:
                self._registry = registry

    def worker_latency(self):
        """``{worker key: histogram summary}`` — the straggler detector's
        input (:func:`petastorm_tpu.obs.analyze.detect_straggler`)."""
        with self._lock:
            hists = dict(self._worker_hists)
        return {key: hist.snapshot() for key, hist in hists.items()}

    # -- evidence/context wiring --------------------------------------------------------

    def _add(self, table, value):
        with self._lock:
            handle = self._next_handle
            self._next_handle += 1
            table[handle] = value
        return handle

    def add_stack_provider(self, fn):
        """Register ``fn() -> {label: stack text}`` (the process pool's
        signal-children-and-collect hook). Returns a removal handle."""
        return self._add(self._stack_providers, fn)

    def remove_stack_provider(self, handle):
        with self._lock:
            self._stack_providers.pop(handle, None)

    def add_context(self, name, fn):
        """Register ``fn() -> dict`` snapshotted into every flight record
        under ``context[name]`` (queue depths, pipeline stats, io gauges)."""
        return self._add(self._contexts, (name, fn))

    def remove_context(self, handle):
        with self._lock:
            self._contexts.pop(handle, None)

    def add_stall_callback(self, fn, prefix=None):
        """Register ``fn(StallError)`` fired under ``escalation="raise"`` (the
        loader uses it to fail the consumer fast). With ``prefix`` (a
        :meth:`scoped` namespace) the callback only fires when a STALLED
        actor belongs to that scope — on a shared monitor, one pipeline's
        stall must not fail every other pipeline's consumer. Returns a
        removal handle."""
        return self._add(self._stall_callbacks, (prefix, fn))

    def remove_stall_callback(self, handle):
        with self._lock:
            self._stall_callbacks.pop(handle, None)

    def add_healer(self, fn):
        """Register ``fn(stalled) -> iterable of actor names it healed`` (the
        process pool's kill-the-hung-child hook, ISSUE 7). ``stalled`` is the
        list of describe dicts from :meth:`check_stalls`. Under
        ``escalation="heal"`` every healer runs; stalled actors NO healer
        claims escalate to :class:`StallError`. Returns a removal handle."""
        return self._add(self._healers, fn)

    def remove_healer(self, handle):
        with self._lock:
            self._healers.pop(handle, None)

    # -- stall detection ----------------------------------------------------------------

    def check_stalls(self, now=None):
        """Actors whose busy-state heartbeat age exceeds their threshold —
        each reported ONCE per hang (re-armed by its next beat). The watchdog
        calls this every poll; tests call it directly."""
        now = now if now is not None else time.monotonic()
        with self._lock:
            hbs = list(self._hbs.values())
        stalled = []
        for hb in hbs:
            if hb.waiting or hb._reported:
                continue
            if hb.age(now) > hb.threshold_s:
                hb._reported = True  # debounce until the actor beats again
                stalled.append(hb.describe(now))
        return stalled

    @property
    def stall_count(self):
        return self._stalls

    @property
    def last_record_path(self):
        """Path of the most recently written flight record (None before any)."""
        return self._last_record_path

    def _handle_stall(self, stalled):
        from petastorm_tpu.obs.log import degradation

        self._stalls += len(stalled)
        actors = ", ".join("%s (%s %.1fs > %.1fs)"
                           % (s["actor"], s["state"], s["age_s"],
                              s["threshold_s"]) for s in stalled)
        self.flight.record("stall", actors=[s["actor"] for s in stalled])
        path = None
        if self.options.escalation in ("flight", "heal", "raise"):
            try:
                path = self.dump_flight_record("stall", stalled=stalled)
            except Exception as e:  # noqa: BLE001 — evidence capture must not
                # kill the watchdog (it re-arms at the next beat)
                logger.warning("flight-record dump failed: %s", e)
        # dump first, log after: the log must point at a record that exists
        # (warn mode writes none — say so rather than send the operator to a
        # missing file, or a stale one from a previous run at the same path)
        degradation(
            "stall_detected",
            "Pipeline stall: %s missed the heartbeat threshold%s", actors,
            ("; see the flight record at %s" % path) if path is not None
            else ("; no flight record (escalation='warn')"
                  if self.options.escalation == "warn"
                  else "; flight-record dump FAILED (see preceding warning)"),
            once=False)
        if self.options.escalation == "heal":
            stalled = self._try_heal(stalled)
            if not stalled:
                return  # every stalled actor healed in place: no fail-fast
            actors = ", ".join("%s (%s %.1fs > %.1fs)"
                               % (s["actor"], s["state"], s["age_s"],
                                  s["threshold_s"]) for s in stalled)
        if self.options.escalation in ("heal", "raise"):
            err = StallError(
                "pipeline stalled: %s%s" % (
                    actors, (" (flight record: %s)" % path) if path else ""))
            with self._lock:
                callbacks = list(self._stall_callbacks.values())
            actors = [s["actor"] for s in stalled]
            for prefix, cb in callbacks:
                if prefix is not None and not any(
                        a.startswith(prefix + "/") for a in actors):
                    continue  # scoped callback: none of ITS actors stalled
                try:
                    cb(err)
                except Exception as e:  # noqa: BLE001 — one bad callback must
                    # not stop the fail-fast delivery to the others
                    logger.warning("stall callback failed: %s", e)

    def _try_heal(self, stalled):
        """Run every registered healer against ``stalled``; returns the
        actors nobody healed (empty = fully recovered). A healed actor's next
        beat re-arms its debounce, so a *re*-hang after a heal is detected
        again — and escalates again, until the healer's budget runs out and
        the leftover stall falls through to :class:`StallError`."""
        from petastorm_tpu.obs.log import degradation

        with self._lock:
            healers = list(self._healers.values())
        remaining = list(stalled)
        for fn in healers:
            if not remaining:
                break
            try:
                healed = set(fn(remaining) or ())
            except Exception as e:  # noqa: BLE001 — a broken healer must not
                # kill the watchdog; the stall then escalates instead
                logger.warning("stall healer failed: %s", e)
                continue
            if healed:
                remaining = [s for s in remaining if s["actor"] not in healed]
        healed_n = len(stalled) - len(remaining)
        if healed_n:
            self._heals += healed_n
            self.flight.record("heal", healed=healed_n,
                               remaining=[s["actor"] for s in remaining])
            degradation(
                "stall_healed",
                "Stall auto-heal recovered %d actor(s) in place%s", healed_n,
                ("; %d still stalled (escalating)" % len(remaining))
                if remaining else "", once=False)
        return remaining

    @property
    def heal_count(self):
        """Actors recovered in place by the ``heal`` escalation tier."""
        return self._heals

    # -- flight record ------------------------------------------------------------------

    def dump_flight_record(self, reason, stalled=(), path=None):
        """Capture + atomically write one flight record; returns its path.

        The record is self-contained JSON: stalled actors, every heartbeat,
        all driver thread stacks, child stacks from registered providers,
        context snapshots (queue depths / stats / io), degradation counts,
        per-worker latency summaries, and the event ring.
        """
        record = self.capture(reason, stalled=stalled)
        path = path or self.options.flight_path
        write_flight_record(path, record)
        self._last_record_path = path
        return path

    def capture(self, reason, stalled=()):
        """The flight-record dict (no file IO) — ``health_report()``'s body."""
        from petastorm_tpu.obs.log import degradation_counts

        with self._lock:
            providers = list(self._stack_providers.values())
            contexts = list(self._contexts.values())
        child_stacks = {}
        for fn in providers:
            try:
                child_stacks.update(fn() or {})
            except Exception as e:  # noqa: BLE001 — partial evidence beats none
                child_stacks["<provider error>"] = repr(e)
        context = {}
        for name, fn in contexts:
            try:
                context[name] = fn()
            except Exception as e:  # noqa: BLE001 — partial evidence beats none
                context[name] = {"error": repr(e)}
        return {
            "schema": "ptpu-flight-v1",
            "ts": time.time(),
            "pid": os.getpid(),
            "reason": reason,
            "stalls_total": self._stalls,
            "stalled": list(stalled),
            "heartbeats": self.heartbeats(),
            "driver_stacks": driver_thread_stacks(),
            "child_stacks": child_stacks,
            "context": context,
            "degradations": degradation_counts(),
            "worker_latency": self.worker_latency(),
            "events": self.flight.events(),
        }

    # -- metrics export -----------------------------------------------------------------

    def collect(self):
        """Pull-mode collector payload (registered by the loader's metrics
        wiring as the ``ptpu_health_*`` family): per-actor heartbeat age and
        stalled flag, plus the stall total."""
        now = time.monotonic()
        out = {"stalls_total": self._stalls, "heals_total": self._heals}
        with self._lock:
            hbs = list(self._hbs.values())
        for hb in hbs:
            key = _sanitize(hb.name)
            out["hb_age_s_" + key] = round(hb.age(now), 3)
            out["hb_stalled_" + key] = int(
                not hb.waiting and hb.age(now) > hb.threshold_s)
        return out

    # -- lifecycle ----------------------------------------------------------------------

    def scoped(self, prefix):
        """A :class:`HealthScope` namespacing actor registrations under
        ``prefix`` — required when one monitor watches several pipelines."""
        return HealthScope(self, prefix)

    def start(self):
        """Arm the watchdog daemon + activate the flight recorder. Idempotent."""
        if self._watchdog is not None and self._watchdog.is_alive():
            return self
        self._stop_event.clear()
        activate(self.flight)
        self._watchdog = threading.Thread(
            target=self._watch, name="ptpu-health-watchdog", daemon=True)
        self._watchdog.start()
        return self

    def _watch(self):
        while not self._stop_event.wait(self.options.poll_interval_s):
            try:
                stalled = self.check_stalls()
                if stalled:
                    self._handle_stall(stalled)
            except Exception as e:  # noqa: BLE001 — the watchdog must outlive
                # any single bad poll (it IS the last line of defense)
                logger.warning("health watchdog poll failed: %s", e)

    def stop(self):
        self._stop_event.set()
        deactivate(self.flight)
        watchdog, self._watchdog = self._watchdog, None
        if watchdog is not None:
            watchdog.join(timeout=max(5.0, 2 * self.options.poll_interval_s))

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()


class HealthScope:
    """Namespaced view of a :class:`HealthMonitor` for ONE pipeline.

    The registry is get-or-create by actor NAME — so two pipelines sharing a
    monitor would otherwise hand their producers/workers the SAME heartbeat
    slots, and the healthy pipeline's stamps would mask the hung one's stall
    (plus merge their per-worker latency histograms). A scope prefixes every
    registration and latency key with ``<prefix>/``, giving each pipeline its
    own actors on the shared monitor. Downstream components (executors, the
    readahead pool) duck-type against this surface, so a bare monitor — the
    loader-owned single-pipeline case — works unchanged in their hands.
    """

    def __init__(self, monitor, prefix):
        self.monitor = monitor
        self.prefix = prefix
        self.flight = monitor.flight
        self.options = monitor.options

    def _name(self, name):
        return "%s/%s" % (self.prefix, name)

    def register(self, name, role, threshold_s=None):
        return self.monitor.register(self._name(name), role, threshold_s)

    def unregister(self, name):
        self.monitor.unregister(self._name(name))

    def observe_worker(self, key, dur):
        self.monitor.observe_worker(self._name(str(key)), dur)

    def worker_latency(self):
        """Only THIS scope's workers (straggler detection must compare peers
        within one executor, never across pipelines)."""
        cut = len(self.prefix) + 1
        return {k[cut:]: v for k, v in self.monitor.worker_latency().items()
                if isinstance(k, str) and k.startswith(self.prefix + "/")}

    def add_stack_provider(self, fn):
        return self.monitor.add_stack_provider(fn)

    def remove_stack_provider(self, handle):
        self.monitor.remove_stack_provider(handle)

    def add_healer(self, fn):
        """Forwarded as-is: the healer receives FULL (prefixed) actor names in
        the stalled dicts and must return the same names — the process pool's
        healer rebuilds its own scoped names (via this scope's ``_name``) and
        claims only exact matches, so one pipeline's healer never touches a
        sibling's children on a shared monitor."""
        return self.monitor.add_healer(fn)

    def remove_healer(self, handle):
        self.monitor.remove_healer(handle)

    def close(self):
        """Retire every actor this scope registered (loader ``__exit__`` on a
        shared monitor — the monitor itself stays running for its owner)."""
        self.monitor.unregister_prefix(self.prefix)


def normalize_health(health):
    """``DataLoader(health=...)`` / reader-factory normalization:
    ``None``/``False`` (honoring ``PTPU_HEALTH``) → ``(monitor-or-None,
    owned)``; ``True`` → fresh monitor with default options; a
    :class:`HealthOptions` → fresh monitor with it; a :class:`HealthMonitor`
    → shared as-is (caller keeps ownership)."""
    if isinstance(health, HealthMonitor):
        return health, False
    if isinstance(health, HealthOptions):
        return HealthMonitor(health), True
    if health or (health is None and health_enabled_by_env()):
        return HealthMonitor(), True
    return None, False
