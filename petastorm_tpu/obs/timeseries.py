"""Windowed metric time-series: bounded per-metric rings fed on the Reporter
cadence (ISSUE 12).

Every obs layer so far answers "what is the cumulative state *now*": the
registry's counters only ever grow, histogram percentiles cover the whole run,
and the analyzer/attribution verdicts fold one window with no memory. A
controller that wants to retune without oscillating — and an operator who
wants "did the p99 *move*" — needs windows **over time**. This module adds
them without touching any hot path:

- :class:`TimelineStore` samples a :class:`~petastorm_tpu.obs.metrics
  .MetricsRegistry` on demand (the :class:`~petastorm_tpu.obs.export.Reporter`
  thread calls it once per flush — one pass over the registry, one lock per
  metric, zero cost on the observe/inc paths) and appends one point per series
  to a bounded ring (``deque(maxlen=...)`` — old windows fall off).
- Counters are stored as **deltas → rates** (a counter that moved 1200 in a
  2 s window is a 600/s series point); a counter that *shrank* is treated as a
  restart and charged its current value, so rates stay correct across process
  or Reporter restarts instead of spiking negative.
- Histograms are stored as **per-window percentiles**: the sampler diffs the
  cumulative log-bucket state between flushes and computes p50/p99 of just the
  observations that landed in the window — the series the SLO engine
  (:mod:`petastorm_tpu.obs.slo`) evaluates.
- Every sample notifies registered listeners with the full window, which is
  how the SLO/anomaly engine rides the same cadence.

Points are timestamped on a **(wall, perf) clock-anchor pair** captured once
at store construction: a point's ``t`` is ``anchor_wall + (perf_now -
anchor_perf)``, the same scheme the PR 3/10 trace/provenance merges use — the
wall clock is trusted exactly once, so an NTP step mid-run cannot reorder
windows, and :func:`merge_exports` aligns multiple processes'/hosts' exports
on their anchors instead of each sample's (possibly skewed) wall stamp.

``MetricsRegistry.timeline(name)`` is the read seam; :func:`export_document`
is the JSON shape the scrape endpoint (:mod:`petastorm_tpu.obs.serve`) serves
and ``petastorm-tpu-stats --merge`` consumes.
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time
from collections import deque

#: schema tag on the fleet-export JSON document (the /timelines endpoint and
#: ``petastorm-tpu-stats --merge`` inputs)
EXPORT_SCHEMA = "ptpu-fleet-export-v1"

#: default ring bound per series: at the Reporter's 5 s default cadence this
#: holds ~42 minutes of windows in a few KB per series
DEFAULT_MAX_POINTS = 512

#: series-count cap: a labels-cardinality explosion (one family per item key,
#: say) must not grow the store unbounded — new series beyond the cap are
#: counted in ``TimelineStore.dropped_series``, never silently ignored
DEFAULT_MAX_SERIES = 4096


class MetricTimeline:
    """One metric's bounded point ring. Points are plain dicts (JSON-ready):

    - counters/gauges/collector values: ``{"t", "value", "delta", "rate"}``
      (``delta``/``rate`` are None on a series' first window — there is no
      prior sample to difference against);
    - histograms: ``{"t", "count", "sum", "p50", "p99"}`` where every field
      covers ONLY the window (count of new observations, their percentiles).
    """

    __slots__ = ("name", "kind", "_points")

    def __init__(self, name, kind, max_points=DEFAULT_MAX_POINTS):
        self.name = name
        self.kind = kind
        self._points = deque(maxlen=max(2, int(max_points)))

    def append(self, point):
        self._points.append(point)

    def points(self):
        """Oldest-first list of point dicts (a copy — safe to mutate)."""
        return [dict(p) for p in self._points]

    def __len__(self):
        return len(self._points)


def _window_percentile(buckets, count, q):
    """Percentile upper bound from non-cumulative ``{bound: count}`` window
    buckets (0.0 bound = the underflow bucket, reported as 0.0)."""
    if count <= 0:
        return 0.0
    target = q * count
    cum = 0
    for bound in sorted(buckets):
        cum += buckets[bound]
        if cum >= target:
            return bound
    return max(buckets) if buckets else 0.0


def _decumulate(export_state):
    """``Histogram.export_state()`` → (non-cumulative {bound: count}, count,
    sum)."""
    cum_buckets, count, total = export_state
    out = {}
    prev = 0
    for bound, cum in cum_buckets:
        out[bound] = cum - prev
        prev = cum
    return out, count, total


class TimelineStore:
    """Bounded time-series store over one registry; sampled on demand.

    ``sample()`` is the only write path and is designed to be called from ONE
    cadence thread (the Reporter); it takes the store lock for the whole pass,
    so a second caller serializes rather than corrupting the delta state. The
    registry's metric locks are taken one at a time inside — the instrumented
    hot paths never see more than their usual single-lock acquire.
    """

    def __init__(self, registry, max_points=DEFAULT_MAX_POINTS,
                 max_series=DEFAULT_MAX_SERIES):
        self._registry = registry
        self._max_points = int(max_points)
        self._max_series = int(max_series)
        self._lock = threading.Lock()
        self._series = {}       # name -> MetricTimeline
        self._prev_scalar = {}  # name -> last sampled value
        self._prev_hist = {}    # name -> (non-cum buckets, count, sum)
        self._listeners = []
        #: the clock anchor (satellite: the same pair every export carries):
        #: wall trusted ONCE here, elapsed time measured on the perf clock
        self.anchor_wall = time.time()
        self.anchor_perf = time.perf_counter()
        self._last_perf = None
        #: series refused past ``max_series`` (bounded-store honesty: a
        #: cardinality explosion is VISIBLE, not silently truncated)
        self.dropped_series = 0

    # -- wiring -------------------------------------------------------------------------

    def add_listener(self, fn):
        """Register ``fn(window, t)`` called after every sample with the full
        window dict ``{name: {"kind": ..., **point}}``. Returns ``fn`` (the
        detach token for :meth:`remove_listener`)."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)
        return fn

    def remove_listener(self, fn):
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def anchored_now(self):
        """Current time on the anchored timeline (wall-at-anchor + perf
        elapsed) — immune to wall-clock steps after construction."""
        return self.anchor_wall + (time.perf_counter() - self.anchor_perf)

    # -- sampling -----------------------------------------------------------------------

    def _timeline(self, name, kind):
        tl = self._series.get(name)
        if tl is None:
            if len(self._series) >= self._max_series:
                self.dropped_series += 1
                return None
            tl = MetricTimeline(name, kind, self._max_points)
            self._series[name] = tl
        return tl

    def sample(self):
        """Sample every registry series into the rings; returns the window
        dict ``{name: {"kind": ..., **point}}`` and notifies listeners."""
        with self._lock:
            now_perf = time.perf_counter()
            t = round(self.anchor_wall + (now_perf - self.anchor_perf), 6)
            dt = None if self._last_perf is None else now_perf - self._last_perf
            self._last_perf = now_perf
            window = {}
            for name, kind, payload in self._registry._timeline_sources():
                if kind == "histogram":
                    point = self._sample_hist(name, payload, t)
                else:
                    point = self._sample_scalar(name, kind, payload, t, dt)
                if point is None:
                    continue
                window[name] = dict(point, kind=kind)
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(window, t)
            except Exception:  # noqa: BLE001 — a bad listener must not kill the cadence
                from petastorm_tpu.obs.log import degradation

                degradation("timeline_listener_error",
                            "timeline listener %r raised; window dropped for "
                            "it (series keep sampling)", fn)
        return window

    def _sample_scalar(self, name, kind, value, t, dt):
        tl = self._timeline(name, kind)
        if tl is None:
            return None
        prev = self._prev_scalar.get(name)
        self._prev_scalar[name] = value
        if prev is None:
            point = {"t": t, "value": value, "delta": None, "rate": None}
        else:
            delta = value - prev
            if kind == "counter" and delta < 0:
                # a counter can only shrink across a restart (new process
                # re-registered the family, or a test reset it): the current
                # value IS the window's worth of events
                delta = value
            # rates NEVER go negative (the documented contract): a shrunken
            # gauge-kind series — a real level dropping, or a cumulative
            # collector (ptpu_pipeline_rows/read_s, no *_total suffix) whose
            # pipeline restarted — keeps its honest negative delta but has no
            # meaningful per-second event rate for that window
            rate = None if not dt or delta < 0 else round(delta / dt, 6)
            point = {"t": t, "value": value, "delta": delta, "rate": rate}
        tl.append(point)
        return point

    def _sample_hist(self, name, export_state, t):
        tl = self._timeline(name, "histogram")
        if tl is None:
            return None
        buckets, count, total = _decumulate(export_state)
        prev = self._prev_hist.get(name)
        self._prev_hist[name] = (buckets, count, total)
        if prev is None:
            wbuckets, wcount, wsum = buckets, count, total
        else:
            pbuckets, pcount, psum = prev
            if count < pcount:  # histogram reset (benchmark window re-anchor)
                wbuckets, wcount, wsum = buckets, count, total
            else:
                wbuckets = {b: n - pbuckets.get(b, 0)
                            for b, n in buckets.items()
                            if n - pbuckets.get(b, 0) > 0}
                wcount = count - pcount
                wsum = total - psum
        point = {"t": t, "count": wcount, "sum": round(wsum, 6),
                 "p50": round(_window_percentile(wbuckets, wcount, 0.50), 6),
                 "p99": round(_window_percentile(wbuckets, wcount, 0.99), 6)}
        tl.append(point)
        return point

    # -- reads --------------------------------------------------------------------------

    def points(self, name):
        with self._lock:
            tl = self._series.get(name)
            return tl.points() if tl is not None else []

    def series_names(self):
        with self._lock:
            return sorted(self._series)

    def to_dict(self):
        """``{name: {"kind", "points"}}`` — the export/serve shape."""
        with self._lock:
            return {name: {"kind": tl.kind, "points": tl.points()}
                    for name, tl in self._series.items()}


# -- export / merge ---------------------------------------------------------------------

def export_document(registry, extra=None):
    """The fleet-export JSON document: last snapshot + timelines + the clock
    anchor identifying this source. Served by :mod:`petastorm_tpu.obs.serve`
    at ``/timelines`` and consumed by ``petastorm-tpu-stats --merge``."""
    store = registry.timeline_store()
    doc = {
        "schema": EXPORT_SCHEMA,
        "source": "%s:%d" % (socket.gethostname(), os.getpid()),
        "ts": time.time(),
        "anchor": {"wall": store.anchor_wall, "perf": store.anchor_perf,
                   "host": socket.gethostname(), "pid": os.getpid()},
        "metrics": registry.snapshot(),
        "timelines": store.to_dict(),
        "dropped_series": store.dropped_series,
    }
    if extra:
        doc.update(extra)
    return doc


def _anchored_t(line, anchor=None):
    """A Reporter JSONL line's time on the anchored timeline: trust the
    anchor's wall once and the line's perf elapsed — NOT the line's own wall
    stamp (which may step under NTP / be skewed on another host). The line's
    OWN anchor wins over the caller's fallback: a restarted process appending
    to the same stream carries a fresh (wall, perf) pair, and placing its
    windows via the first run's anchor would throw them onto the wrong epoch
    of the perf clock entirely."""
    line_anchor = line.get("anchor") or anchor
    perf = line.get("perf")
    if line_anchor and perf is not None \
            and line_anchor.get("perf") is not None:
        return line_anchor["wall"] + (perf - line_anchor["perf"])
    return line.get("ts", 0.0)


def export_to_merge_shape(doc, fallback_source="?"):
    """An in-memory ``/timelines`` export document in the common merge
    shape (``{"source", "anchor", "metrics", "series"}``) — the same
    conversion :func:`load_export` applies to a document read from disk.
    The service's ``/fleet`` aggregator (ISSUE 20) runs live piggybacked
    peer documents through this before :func:`merge_exports`."""
    series = {name: tl.get("points", [])
              for name, tl in (doc.get("timelines") or {}).items()}
    return {"source": doc.get("source") or fallback_source,
            "anchor": doc.get("anchor"),
            "metrics": doc.get("metrics") or {},
            "series": series}


def load_export(path):
    """Load one process's export — a ``/timelines`` JSON document or a
    Reporter JSONL stream — into the common merge shape::

        {"source", "anchor", "metrics", "series": {name: [points]}}

    For JSONL streams the scalar series are rebuilt from consecutive
    snapshots (delta/rate between lines; counter shrink = restart), and each
    line is placed on the anchored timeline via the (wall, perf) pair the
    v2 Reporter stamps — older v1 lines fall back to their wall ``ts``.
    """
    with open(path) as f:
        head = f.read(4096)
    if '"%s"' % EXPORT_SCHEMA in head.split("\n", 1)[0]:
        with open(path) as f:
            doc = json.load(f)
        return export_to_merge_shape(
            doc, fallback_source=os.path.basename(path))

    lines = []
    with open(path) as f:
        for raw in f:
            try:
                obj = json.loads(raw)
            except ValueError:
                continue  # torn final line from a live writer
            if isinstance(obj, dict) and "metrics" in obj:
                lines.append(obj)
    if not lines:
        raise ValueError("no snapshots in %s" % path)
    anchor = next((ln.get("anchor") for ln in lines if ln.get("anchor")), None)
    source = os.path.basename(path)
    if anchor and anchor.get("host") is not None:
        source = "%s:%s" % (anchor["host"], anchor.get("pid", "?"))
    series = {}
    prev = {}
    prev_t = None
    for line in lines:
        t = round(_anchored_t(line, anchor), 6)
        # a restarted writer's fresh anchor can begin a new epoch: a
        # non-advancing timeline yields no window length, not a negative one
        dt = None if prev_t is None or t <= prev_t else t - prev_t
        prev_t = t
        for name, value in line["metrics"].items():
            if isinstance(value, dict):  # histogram summary: cumulative view
                series.setdefault(name, []).append(
                    {"t": t, "count": value.get("count", 0),
                     "p50": value.get("p50", 0.0),
                     "p99": value.get("p99", 0.0)})
                continue
            p = prev.get(name)
            prev[name] = value
            if p is None:
                point = {"t": t, "value": value, "delta": None, "rate": None}
            else:
                delta = value - p
                rate = None
                if delta < 0:
                    if name.endswith("_total"):
                        delta = value  # counter restart: current value IS the window
                    # a shrunken level (queue depth, or a cumulative collector
                    # behind a restarted pipeline) has no meaningful event
                    # rate — rates never go negative, the delta stays honest
                if dt and delta >= 0:
                    rate = round(delta / dt, 6)
                point = {"t": t, "value": value, "delta": delta, "rate": rate}
            series.setdefault(name, []).append(point)
    return {"source": source, "anchor": anchor,
            "metrics": lines[-1]["metrics"], "series": series}


def uniquify_sources(exports):
    """Deterministically disambiguate colliding source names (two exports of
    one host:pid — twin registries in one process, a rotated pair): the
    second same-named export becomes ``name#2`` and so on. Both merge and
    fleet-series grouping go through this, so the names agree."""
    seen = {}
    out = []
    for export in exports:
        source = export["source"]
        count = seen.get(source, 0) + 1
        seen[source] = count
        if count > 1:
            export = dict(export, source="%s#%d" % (source, count))
        out.append(export)
    return out


def merge_exports(exports):
    """Aggregate per-process exports into the fleet view.

    ``totals`` is unit-pinned: every scalar family is the SUM of the sources'
    last snapshots (counters add; additive gauges like queue depths add too —
    a fleet has that many items queued). Histogram summaries merge as summed
    count/sum and the MAX of the sources' percentiles — a conservative upper
    bound (true fleet percentiles need the buckets, which JSONL summaries do
    not carry; the Prometheus endpoint serves full buckets for scrapers that
    want exact fleet quantiles).
    """
    totals = {}
    per_source = {}
    for export in uniquify_sources(exports):
        per_source[export["source"]] = export["metrics"]
        for name, value in export["metrics"].items():
            if isinstance(value, dict):
                agg = totals.setdefault(
                    name, {"count": 0, "sum": 0.0, "mean": 0.0,
                           "p50": 0.0, "p90": 0.0, "p99": 0.0})
                agg["count"] += value.get("count", 0)
                agg["sum"] += value.get("sum", 0.0)
                for q in ("p50", "p90", "p99"):
                    agg[q] = max(agg[q], value.get(q, 0.0))
                agg["mean"] = agg["sum"] / agg["count"] if agg["count"] else 0.0
            else:
                totals[name] = totals.get(name, 0) + value
    return {"sources": sorted(per_source), "totals": totals,
            "per_source": per_source}


def fleet_rate_series(exports, name, bin_s=5.0):
    """Fleet-total rate of one counter family: each source's rate points are
    binned onto the common anchored timeline (mean rate per source per bin,
    sources summed per bin). Returns ``[(bin_start_t, fleet_rate)]`` ascending
    — the merge panels' sparkline input."""
    bins = {}  # bin index -> {source: [rates]}
    for export in uniquify_sources(exports):
        for point in export["series"].get(name, ()):
            rate = point.get("rate")
            if rate is None:
                continue
            idx = int(point["t"] // bin_s)
            bins.setdefault(idx, {}).setdefault(
                export["source"], []).append(rate)
    out = []
    for idx in sorted(bins):
        total = sum(sum(rates) / len(rates)
                    for rates in bins[idx].values())
        out.append((idx * bin_s, round(total, 6)))
    return out


# -- rendering helpers ------------------------------------------------------------------

_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def sparkline(values, width=24):
    """Unicode sparkline of the last ``width`` values (min-max normalized;
    None values render as spaces). Empty/flat series render as a flat line."""
    vals = list(values)[-width:]
    if not vals:
        return ""
    present = [v for v in vals if v is not None]
    if not present:
        return " " * len(vals)
    lo, hi = min(present), max(present)
    span = hi - lo
    chars = []
    for v in vals:
        if v is None:
            chars.append(" ")
        elif span <= 0:
            chars.append(_SPARK_CHARS[0])
        else:
            idx = int((v - lo) / span * (len(_SPARK_CHARS) - 1))
            chars.append(_SPARK_CHARS[idx])
    return "".join(chars)
