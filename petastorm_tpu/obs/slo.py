"""SLO + anomaly engine: declarative objectives evaluated per time-series
window, with debounced alerts that name the culprit site (ISSUE 12).

The temporal plane (:mod:`petastorm_tpu.obs.timeseries`) turns the registry
into windowed series; this module watches them. Two detection modes:

- **SLO specs** (:class:`SloSpec`): declarative "this series must stay on this
  side of this threshold" objectives — loader step p99 ≤ X, quarantine rate
  ≤ Y/s, mem-tier hit share ≥ Z, producer idle share ≤ W. Evaluated on every
  window; a spec must breach ``breach_windows`` CONSECUTIVE windows before the
  alert fires (burn-rate debounce — one slow window on a shared host is not an
  incident), fires exactly once per excursion, and re-arms only after a clean
  window.
- **Anomaly detection** (:class:`AnomalyDetector`): for series without a known
  threshold, EWMA-smoothed robust-z drift detection against the trailing
  window history (median/MAD — one outlier window cannot drag the baseline).
  A step cliff fires exactly once: the detector latches while the series stays
  out of band and re-arms when the baseline adapts or the series recovers.

Every firing is a first-class degradation event (``cause=slo_breach`` /
``anomaly_detected`` — counted on ``ptpu_degradations_total``, warn-once
logged, mirrored into every live flight recorder) and carries an
**attribution snapshot** when the engine was given an attribution source
(``DataLoader(slos=...)`` wires ``attribution_report()`` automatically when
provenance is on): the alert names the culprit SITE eating the critical path
("io.remote"), not just the breached symptom.

Zero hot-path cost: evaluation happens on the sampling cadence (the Reporter
thread), never on the loader/reader paths.
"""
from __future__ import annotations

import dataclasses
import re
import threading
import time

_OPS = {
    "<=": lambda v, t: v <= t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    ">": lambda v, t: v > t,
}

#: stats resolvable from a window point (see SloSpec.stat)
_STATS = ("value", "delta", "rate", "p50", "p99", "share")

_LABEL_RES = {
    "tenant": re.compile(r'tenant="([^"]*)"'),
    "worker": re.compile(r'worker="([^"]*)"'),
}


def strip_label(full_name, label):
    """``'base{a="1",tenant="x"}'`` → ``('base{a="1"}', 'x')`` for
    ``label='tenant'``; a series without that label returns
    ``(full_name, None)``. Per-dimension spec expansion (``per_tenant`` /
    ``per_worker``) uses this to match every labeled twin of one base
    metric; the fleet advisor reads worker-labeled series the same way."""
    m = _LABEL_RES[label].search(full_name)
    if m is None:
        return full_name, None
    value = m.group(1)
    base = full_name[:m.start()] + full_name[m.end():]
    base = base.replace("{,", "{").replace(",,", ",").replace(",}", "}")
    if base.endswith("{}"):
        base = base[:-2]
    return base, value


def _strip_tenant(full_name):
    return strip_label(full_name, "tenant")


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One declarative objective over one windowed series.

    ``metric`` is the snapshot full name (labels included), e.g.
    ``'ptpu_pipeline_stage_seconds{stage="read"}'``. ``stat`` picks the window
    statistic:

    - ``value`` — the sampled level (gauges);
    - ``delta`` / ``rate`` — the window's counter movement / per-second rate;
    - ``p50`` / ``p99`` — the window-local histogram percentile;
    - ``share`` — ``delta(metric) / Σ delta(denominator)``; with
      ``denominator=None`` the denominator is the window length in seconds
      (a *time share*: ``metric='ptpu_pipeline_put_wait_s', stat='share'``
      is the producer's idle fraction).

    A window where the series is absent, has no prior sample to delta
    against, or (for histograms) saw fewer than ``min_count`` observations is
    SKIPPED — it neither breaches nor clears, so sparse windows cannot flap
    the debounce state.
    """

    name: str
    metric: str
    stat: str = "value"
    op: str = "<="
    threshold: float = 0.0
    #: for ``stat='share'``: denominator series name(s), deltas summed;
    #: None = the window duration (time share)
    denominator: tuple | str | None = None
    #: consecutive breaching windows before the alert fires (burn debounce)
    breach_windows: int = 2
    #: histogram windows with fewer observations than this are skipped
    min_count: int = 1
    description: str = ""
    #: per-tenant dimensioning (ISSUE 18): evaluate this spec independently
    #: against EVERY ``metric{...,tenant="X"}`` series in the window —
    #: debounce streaks and latches are kept per (spec, tenant), and a firing
    #: alert names the culprit tenant alongside the culprit site
    per_tenant: bool = False
    #: per-worker dimensioning (ISSUE 20): the same expansion over
    #: ``metric{...,worker="X"}`` twins — the data service's straggler alert
    #: debounces independently per decode worker and names the worker id
    per_worker: bool = False

    def __post_init__(self):
        if self.op not in _OPS:
            raise ValueError("SloSpec op must be one of %s, got %r"
                             % (sorted(_OPS), self.op))
        if self.stat not in _STATS:
            raise ValueError("SloSpec stat must be one of %s, got %r"
                             % (_STATS, self.stat))

    def resolve(self, window, window_s=None, metric=None):
        """The spec's statistic from one window dict, or None to skip.
        ``metric`` overrides the looked-up series name (per-tenant expansion
        resolves the same spec against each tenant-labeled twin)."""
        point = window.get(metric if metric is not None else self.metric)
        if point is None:
            return None
        if self.stat in ("p50", "p99"):
            if point.get("count", 0) < self.min_count:
                return None
            return point.get(self.stat)
        if self.stat == "value":
            return point.get("value")
        if self.stat in ("delta", "rate"):
            return point.get(self.stat)  # None on a series' first window
        # share
        num = point.get("delta")
        if num is None:
            return None
        if self.denominator is None:
            if not window_s:
                return None
            return num / window_s
        denoms = (self.denominator,) if isinstance(self.denominator, str) \
            else tuple(self.denominator)
        total = 0.0
        for name in denoms:
            dpoint = window.get(name)
            if dpoint is None or dpoint.get("delta") is None:
                return None
            total += dpoint["delta"]
        if total <= 0:
            return None  # nothing moved: no share to judge
        return num / total

    def ok(self, value):
        return _OPS[self.op](value, self.threshold)


class AnomalyDetector:
    """EWMA + robust-z drift detector over one series' window values.

    ``observe(value)`` returns True exactly when an anomaly FIRES: the
    EWMA-smoothed value sits more than ``z_threshold`` robust standard
    deviations (median/MAD over the trailing ``history`` windows) from the
    baseline, with at least ``min_history`` windows of history. The detector
    then latches — an injected step cliff fires ONCE, not once per window —
    and re-arms when the smoothed series returns within ``z_clear`` (either
    the series recovered, or the trailing baseline adapted to the new
    normal)."""

    def __init__(self, history=32, min_history=8, z_threshold=6.0,
                 z_clear=3.0, ewma_alpha=0.4):
        from collections import deque

        self._history = deque(maxlen=max(min_history, int(history)))
        self._min_history = int(min_history)
        self._z_threshold = float(z_threshold)
        self._z_clear = float(z_clear)
        self._alpha = float(ewma_alpha)
        self._ewma = None
        self._fired = False
        self.last_z = 0.0

    def _z(self, value):
        vals = sorted(self._history)
        n = len(vals)
        med = vals[n // 2] if n % 2 else 0.5 * (vals[n // 2 - 1] + vals[n // 2])
        devs = sorted(abs(v - med) for v in vals)
        mad = devs[n // 2] if n % 2 else 0.5 * (devs[n // 2 - 1] + devs[n // 2])
        scale = 1.4826 * mad
        if scale <= 0:
            # a perfectly flat baseline: any departure is infinitely many
            # MADs away — use a small floor relative to the median instead
            scale = max(abs(med) * 0.05, 1e-9)
        return abs(value - med) / scale

    def observe(self, value):
        if value is None:
            return False
        if self._ewma is None:
            self._ewma = float(value)
        else:
            self._ewma = (self._alpha * float(value)
                          + (1.0 - self._alpha) * self._ewma)
        fired = False
        if len(self._history) >= self._min_history:
            z = self._z(self._ewma)
            self.last_z = round(z, 3)
            if not self._fired and z >= self._z_threshold:
                self._fired = True
                fired = True
            elif self._fired and z <= self._z_clear:
                self._fired = False  # recovered / baseline adapted: re-arm
        self._history.append(float(value))
        return fired


@dataclasses.dataclass
class SloAlert:
    """One debounced firing (breach or anomaly)."""

    name: str
    cause: str          # slo_breach | anomaly_detected
    metric: str
    stat: str
    t: float            # anchored window time
    value: float
    threshold: float | None   # None for anomalies
    windows: int        # consecutive breaching windows at fire time
    message: str
    #: AttributionReport.to_dict() at fire time (None without an attribution
    #: source) — the alert names the culprit site, not just the symptom
    attribution: dict | None = None
    #: the attribution snapshot's slow-decile culprit site (convenience)
    culprit: str | None = None
    #: culprit tenant for ``per_tenant`` specs (ISSUE 18): the tenant whose
    #: series breached — None for untagged specs and anomalies
    tenant: str | None = None
    #: culprit worker for ``per_worker`` specs (ISSUE 20): the decode worker
    #: whose series breached — the data service's straggler alert names it
    worker: str | None = None

    def to_dict(self):
        return dataclasses.asdict(self)


class SloEngine:
    """Evaluates :class:`SloSpec`s (+ anomaly watches) per sampled window.

    Attach to a :class:`~petastorm_tpu.obs.timeseries.TimelineStore` with
    :meth:`attach` (the Reporter's ``sample_timelines()`` cadence then drives
    evaluation), or call :meth:`evaluate` directly with a window dict (tests,
    manual cadences). ``attribution`` is a zero-arg callable returning an
    :class:`~petastorm_tpu.obs.critical_path.AttributionReport` (or None);
    ``DataLoader(slos=...)`` wires its ``attribution_report`` when provenance
    is enabled. Alerts are kept in a bounded list (newest last) and counted
    as ``ptpu_slo_alerts_total{slo=...}`` on the engine's registry."""

    def __init__(self, specs=(), registry=None, attribution=None,
                 anomaly_metrics=(), anomaly_kwargs=None, max_alerts=256):
        self._specs = list(specs)
        self._registry = registry
        self._attribution = attribution
        #: [(metric, stat)] series watched for anomalies without a threshold
        self._anomaly_watch = [(m, s) for m, s in
                               (tuple(w) for w in anomaly_metrics)]
        self._anomaly_kwargs = dict(anomaly_kwargs or {})
        self._detectors = {}
        self._lock = threading.Lock()
        self._alerts = []
        self._max_alerts = int(max_alerts)
        self._breach_streak = {}   # spec name -> consecutive breaching windows
        self._breach_latched = {}  # spec name -> alert already fired this excursion
        self._last_t = None
        self._store = None
        self._listener = None
        self.windows_evaluated = 0

    # -- wiring -------------------------------------------------------------------------

    def set_attribution(self, fn):
        self._attribution = fn

    def attach(self, store):
        """Subscribe to a TimelineStore's sampling cadence. Idempotent per
        store; :meth:`detach` unsubscribes (loader ``__exit__``)."""
        self.detach()
        self._store = store
        self._listener = store.add_listener(self._on_window)
        return self

    def detach(self):
        store, self._store = self._store, None
        if store is not None and self._listener is not None:
            store.remove_listener(self._listener)
        self._listener = None

    def _on_window(self, window, t):
        self.evaluate(window, t)

    # -- evaluation ---------------------------------------------------------------------

    def evaluate(self, window, t=None):
        """Evaluate all specs + anomaly watches against one window; returns
        the alerts fired by THIS window (possibly empty)."""
        t = time.time() if t is None else t
        with self._lock:
            window_s = None if self._last_t is None else max(0.0, t - self._last_t)
            self._last_t = t
            self.windows_evaluated += 1
            fired = []
            for spec in self._specs:
                if spec.per_tenant or spec.per_worker:
                    # per-dimension expansion (ISSUE 18/20): one independent
                    # debounce per labeled twin of the base series
                    label = "tenant" if spec.per_tenant else "worker"
                    for series in window:
                        base, who = strip_label(series, label)
                        if who is None or base != spec.metric:
                            continue
                        value = spec.resolve(window, window_s=window_s,
                                             metric=series)
                        self._judge(spec, value, fired,
                                    **{label: who})
                    continue
                value = spec.resolve(window, window_s=window_s)
                self._judge(spec, value, fired)
            anomalies = []
            for metric, stat in self._anomaly_watch:
                point = window.get(metric)
                value = None if point is None else point.get(stat)
                key = (metric, stat)
                det = self._detectors.get(key)
                if det is None:
                    det = self._detectors[key] = AnomalyDetector(
                        **self._anomaly_kwargs)
                if det.observe(value):
                    anomalies.append((metric, stat, value, det.last_z))
        out = []
        for spec, value, streak, tenant, worker in fired:
            out.append(self._fire_breach(spec, value, streak, t,
                                         tenant=tenant, worker=worker))
        for metric, stat, value, z in anomalies:
            out.append(self._fire_anomaly(metric, stat, value, z, t))
        return out

    def _judge(self, spec, value, fired, tenant=None, worker=None):
        """One spec × one (possibly tenant-/worker-dimensioned) value through
        the debounce state machine. Caller holds ``self._lock``."""
        if value is None:
            return  # sparse window: neither breaches nor clears
        if worker is not None:
            key = (spec.name, "worker", worker)
        elif tenant is not None:
            key = (spec.name, tenant)
        else:
            key = spec.name
        if spec.ok(value):
            self._breach_streak[key] = 0
            self._breach_latched[key] = False
            return
        streak = self._breach_streak.get(key, 0) + 1
        self._breach_streak[key] = streak
        if streak >= spec.breach_windows \
                and not self._breach_latched.get(key):
            self._breach_latched[key] = True
            fired.append((spec, value, streak, tenant, worker))

    # -- alert plumbing -----------------------------------------------------------------

    def _attribution_snapshot(self, tenant=None):
        if self._attribution is None:
            return None, None
        try:
            if tenant is not None:
                # tenant-scoped attribution when the source takes the kwarg
                # (ProvenanceRecorder/DataLoader do); older callables fall
                # back to the unscoped report
                try:
                    report = self._attribution(tenant=tenant)
                except TypeError:
                    report = self._attribution()
            else:
                report = self._attribution()
        except Exception:  # noqa: BLE001 — a broken source must not kill alerting
            from petastorm_tpu.obs.log import degradation

            degradation("slo_attribution_error",
                        "SLO alert attribution snapshot failed; alert carries "
                        "no culprit")
            return None, None
        if report is None:
            return None, None
        return report.to_dict(), report.slow_top

    def _record_alert(self, alert):
        from petastorm_tpu.obs import flight as _flight
        from petastorm_tpu.obs.log import degradation

        with self._lock:
            self._alerts.append(alert)
            del self._alerts[:-self._max_alerts]
        if self._registry is not None:
            labels = {"slo": alert.name}
            if alert.tenant is not None:
                labels["tenant"] = alert.tenant
            if alert.worker is not None:
                labels["worker"] = alert.worker
            self._registry.counter(
                "ptpu_slo_alerts_total",
                help="debounced SLO-breach/anomaly alerts", **labels).inc()
        # count + warn-once log + flight mirror of the CAUSE; then the full
        # alert (culprit included) into every live flight recorder
        degradation(alert.cause, "%s", alert.message)
        for recorder in _flight.active_recorders():
            recorder.record("slo_alert", name=alert.name, cause=alert.cause,
                            metric=alert.metric, value=alert.value,
                            threshold=alert.threshold, culprit=alert.culprit,
                            tenant=alert.tenant, worker=alert.worker)
        return alert

    def _fire_breach(self, spec, value, streak, t, tenant=None, worker=None):
        attribution, culprit = self._attribution_snapshot(tenant=tenant)
        who = ""
        if tenant is not None:
            who = " by tenant %r" % tenant
        elif worker is not None:
            who = " by worker %r" % worker
        message = ("SLO %r breached%s: %s %s = %.6g violates %s %.6g for %d "
                   "consecutive windows%s"
                   % (spec.name, who,
                      spec.metric, spec.stat, value, spec.op,
                      spec.threshold, streak,
                      " — critical path owned by %s" % culprit
                      if culprit else ""))
        return self._record_alert(SloAlert(
            name=spec.name, cause="slo_breach", metric=spec.metric,
            stat=spec.stat, t=t, value=round(float(value), 6),
            threshold=spec.threshold, windows=streak, message=message,
            attribution=attribution, culprit=culprit, tenant=tenant,
            worker=worker))

    def _fire_anomaly(self, metric, stat, value, z, t):
        attribution, culprit = self._attribution_snapshot()
        message = ("anomaly on %s %s: window value %.6g sits %.1f robust "
                   "stddevs from the trailing baseline%s"
                   % (metric, stat, value, z,
                      " — critical path owned by %s" % culprit
                      if culprit else ""))
        return self._record_alert(SloAlert(
            name="anomaly:%s:%s" % (metric, stat), cause="anomaly_detected",
            metric=metric, stat=stat, t=t, value=round(float(value), 6),
            threshold=None, windows=1, message=message,
            attribution=attribution, culprit=culprit))

    # -- reads --------------------------------------------------------------------------

    def alerts(self):
        """All alerts so far (oldest first, bounded at ``max_alerts``)."""
        with self._lock:
            return list(self._alerts)

    def breaching(self):
        """Specs currently in a breach streak: ``{name: streak}`` —
        per-tenant expansions key as ``'name{tenant="x"}'`` and per-worker
        ones as ``'name{worker="x"}'``."""

        def _render(key):
            if isinstance(key, str):
                return key
            if len(key) == 3:
                return '%s{worker="%s"}' % (key[0], key[2])
            return '%s{tenant="%s"}' % key

        with self._lock:
            return {_render(n): s
                    for n, s in self._breach_streak.items() if s}

    def collect(self):
        """Pull-collector shape (``ptpu_slo_*``): alert totals + live breach
        streaks, for registries that want the engine state exported."""
        with self._lock:
            return {"alerts": len(self._alerts),
                    "windows_evaluated": self.windows_evaluated,
                    "breaching": sum(1 for s in self._breach_streak.values()
                                     if s)}
