"""Unified pipeline observability (ISSUE 3): metrics, exporters, log, analyzer.

Three pillars on one substrate:

- :mod:`petastorm_tpu.obs.metrics` — process-wide registry of counters, gauges
  and log-bucketed histograms (p50/p90/p99 without stored samples). Components
  keep the ``trace.py`` contract: disabled costs one ``is None`` check per site.
- :mod:`petastorm_tpu.obs.export` — Prometheus text-format file export and a
  background JSONL snapshot reporter; ``petastorm-tpu-stats`` pretty-prints them.
- :mod:`petastorm_tpu.obs.analyze` — the bottleneck analyzer: names the limiting
  pipeline stage (producer-bound / wire-bound / consumer-bound) from the stage
  counters and queue-occupancy gauges (``DataLoader.bottleneck_report()``).

:mod:`petastorm_tpu.obs.log` routes warn-once degradation messages (shm wire
fallbacks, worker deaths, join timeouts) through one structured logger with a
``ptpu_degradations_total{cause=...}`` counter per cause.

The ACTIVE layer (ISSUE 5) sits on top: :mod:`petastorm_tpu.obs.health` stamps
per-actor heartbeats through the whole pipeline and runs a backpressure-aware
stall watchdog; :mod:`petastorm_tpu.obs.flight` keeps the bounded event ring
dumped as a structured flight record on stall, crash, or demand
(``DataLoader.health_report()``); ``petastorm-tpu-stats --watch`` renders it
all as a live terminal dashboard.

The TEMPORAL plane (ISSUE 12) adds windows over time:
:mod:`petastorm_tpu.obs.timeseries` keeps bounded per-metric rings sampled on
the Reporter cadence (counters as rates, histograms as per-window p50/p99);
:mod:`petastorm_tpu.obs.slo` evaluates declarative :class:`SloSpec`s +
robust-z anomaly detection per window, firing debounced alerts that carry an
attribution snapshot naming the culprit site;
:mod:`petastorm_tpu.obs.serve` is the opt-in loopback HTTP scrape endpoint
(Prometheus text + JSON timelines) that ``petastorm-tpu-stats --merge``
aggregates into fleet panels.

The TENANT plane (ISSUE 18): :mod:`petastorm_tpu.obs.tenant` threads a
validated :class:`TenantContext` (bounded slug — always a safe metric label)
through every layer a batch touches, so shared resources answer "who ate
it?" — ``tenant=``-labeled twins beside every untagged total, a
fleet-mergeable :class:`TenantUsageReport`, per-tenant ``SloSpec``
dimensioning, and a tenant panel in ``petastorm-tpu-stats``. See
docs/observability.md "Tenant accounting".
"""
from petastorm_tpu.obs.flight import FlightRecorder
from petastorm_tpu.obs.health import HealthMonitor, HealthOptions
from petastorm_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)
from petastorm_tpu.obs.serve import MetricsServer
from petastorm_tpu.obs.slo import AnomalyDetector, SloEngine, SloSpec
from petastorm_tpu.obs.tenant import TenantContext, TenantUsageReport
from petastorm_tpu.obs.timeseries import TimelineStore

__all__ = ["AnomalyDetector", "Counter", "FlightRecorder", "Gauge",
           "HealthMonitor", "HealthOptions", "Histogram", "MetricsRegistry",
           "MetricsServer", "SloEngine", "SloSpec", "TenantContext",
           "TenantUsageReport", "TimelineStore", "default_registry"]
