"""Unified pipeline observability (ISSUE 3): metrics, exporters, log, analyzer.

Three pillars on one substrate:

- :mod:`petastorm_tpu.obs.metrics` — process-wide registry of counters, gauges
  and log-bucketed histograms (p50/p90/p99 without stored samples). Components
  keep the ``trace.py`` contract: disabled costs one ``is None`` check per site.
- :mod:`petastorm_tpu.obs.export` — Prometheus text-format file export and a
  background JSONL snapshot reporter; ``petastorm-tpu-stats`` pretty-prints them.
- :mod:`petastorm_tpu.obs.analyze` — the bottleneck analyzer: names the limiting
  pipeline stage (producer-bound / wire-bound / consumer-bound) from the stage
  counters and queue-occupancy gauges (``DataLoader.bottleneck_report()``).

:mod:`petastorm_tpu.obs.log` routes warn-once degradation messages (shm wire
fallbacks, worker deaths, join timeouts) through one structured logger with a
``ptpu_degradations_total{cause=...}`` counter per cause.
"""
from petastorm_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "default_registry"]
