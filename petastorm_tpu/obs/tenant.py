"""Per-tenant accounting plane (ISSUE 18): who ate the shared resource?

Every observability plane below this module — provenance (ISSUE 10),
time-series/SLO (ISSUE 12), the cache arena counters (ISSUE 17) — aggregates
all consumers of a host into one anonymous stream. A multi-tenant dispatcher
built on that substrate could never bill a noisy neighbor. This module is the
missing dimension: a validated, *bounded* tenant label threaded through every
layer a batch touches, so decode seconds, store bytes, hedged GETs, arena
resident bytes and quarantined rows all answer "who ate it?".

Three pieces:

- :class:`TenantContext` — tenant id + job id + priority hint. The tenant id
  is a validated slug (``[a-z0-9][a-z0-9._-]{0,31}``) so it is ALWAYS a
  bounded metric label: the cardinality cap
  (:data:`petastorm_tpu.obs.timeseries.DEFAULT_MAX_SERIES`) never has to
  defend against tenant labels, and graftlint GL-O005 whitelists them
  statically for the same reason.
- resolution + propagation — explicit ``make_reader(tenant=)`` /
  ``DataLoader(tenant=)`` argument wins, then the ``PTPU_TENANT`` /
  ``PTPU_TENANT_JOB`` / ``PTPU_TENANT_PRIORITY`` environment (which is also
  how pool children inherit the parent's tenant: the executor stamps the env,
  ``_child_worker`` calls :func:`attach_from_env`). Worker threads activate
  the context thread-locally around each item so IO layers deep in the stack
  (tiers, arena, remote) can ask :func:`current_label` without plumbing.
  An invalid env slug degrades (``tenant_label_invalid``) instead of raising
  — a typo in a launcher script must not kill the job; an invalid *explicit*
  argument raises, because the caller is right there to fix it.
- the meter — ``ptpu_tenant_*`` counter families charged at the resource
  sites, plus :class:`TenantUsageReport`, a fleet-mergeable rollup built from
  any metrics snapshot (a live registry's, a JSONL export's, or the summed
  totals of ``petastorm-tpu-stats --merge``).

The disabled cost contract matches ``trace.py``: with no tenant resolved,
every instrumented site pays one ``is None`` check (``current()`` is a
thread-local read + a module global read) — the ``tenants --smoke`` bench
asserts the whole plane at <=1% untagged overhead. Untagged runs charge
NOTHING here: the pre-existing untagged families remain the single source of
totals, and per-tenant twins appear only when a tenant is known, so
cross-tenant sums reconcile exactly against the untagged totals (no
double-count, no leak).
"""
from __future__ import annotations

import os
import re
import threading
import time
from contextlib import contextmanager

#: the reserved label rendered for unattributed flow in reports and panels.
#: Never a valid tenant id (the slug grammar forbids it), so it cannot
#: collide with a real tenant.
UNTAGGED = "-"

#: bounded-slug grammar — lowercase alphanumerics plus ``._-``, 1..32 chars,
#: leading alphanumeric. Bounded length + restricted alphabet keep
#: ``tenant=`` a safe metric label (and a safe 1-byte-length wire field).
_SLUG_RE = re.compile(r"^[a-z0-9][a-z0-9._-]{0,31}$")

_PRIORITIES = ("low", "normal", "high")

#: env knobs (resolution order: explicit argument > environment > none)
ENV_TENANT = "PTPU_TENANT"
ENV_JOB = "PTPU_TENANT_JOB"
ENV_PRIORITY = "PTPU_TENANT_PRIORITY"


def valid_slug(value):
    """True when ``value`` is a legal bounded tenant/job slug."""
    return isinstance(value, str) and _SLUG_RE.match(value) is not None


class TenantContext:
    """Who this pipeline's work is billed to.

    Immutable-by-convention (instances are shared across threads and pickled
    into pool workers); compares and hashes by value so plan stamping and
    per-tenant dict keys behave.
    """

    __slots__ = ("tenant", "job", "priority")

    def __init__(self, tenant, job=None, priority=None):
        if not valid_slug(tenant):
            raise ValueError(
                "tenant id %r is not a bounded slug (%s) — tenant ids become "
                "metric labels and wire-frame fields, so they must be small "
                "and closed-alphabet" % (tenant, _SLUG_RE.pattern))
        if job is not None and not valid_slug(job):
            raise ValueError("tenant job id %r is not a bounded slug (%s)"
                             % (job, _SLUG_RE.pattern))
        if priority is not None and priority not in _PRIORITIES:
            raise ValueError("tenant priority %r not in %r"
                             % (priority, _PRIORITIES))
        object.__setattr__(self, "tenant", tenant)
        object.__setattr__(self, "job", job)
        object.__setattr__(self, "priority", priority)

    def __setattr__(self, name, value):
        raise AttributeError("TenantContext is immutable")

    def __eq__(self, other):
        return (isinstance(other, TenantContext)
                and other.tenant == self.tenant and other.job == self.job
                and other.priority == self.priority)

    def __ne__(self, other):
        return not self.__eq__(other)

    def __hash__(self):
        return hash((self.tenant, self.job, self.priority))

    def __repr__(self):
        parts = [repr(self.tenant)]
        if self.job:
            parts.append("job=%r" % self.job)
        if self.priority:
            parts.append("priority=%r" % self.priority)
        return "TenantContext(%s)" % ", ".join(parts)

    def __getstate__(self):
        return (self.tenant, self.job, self.priority)

    def __setstate__(self, state):
        tenant, job, priority = state
        object.__setattr__(self, "tenant", tenant)
        object.__setattr__(self, "job", job)
        object.__setattr__(self, "priority", priority)

    def env(self):
        """The env-var dict that propagates this context to a child process
        (the executor merges it into the child env)."""
        out = {ENV_TENANT: self.tenant}
        if self.job:
            out[ENV_JOB] = self.job
        if self.priority:
            out[ENV_PRIORITY] = self.priority
        return out


def from_env(environ=None):
    """The environment's tenant context, or None.

    An invalid ``PTPU_TENANT`` slug fires the ``tenant_label_invalid``
    degradation and resolves to None (untagged) instead of raising: the env
    path is set by launchers, and a launcher typo must degrade attribution,
    not kill the job. Invalid job/priority fields are dropped the same way
    but keep the (valid) tenant id.
    """
    environ = os.environ if environ is None else environ
    raw = environ.get(ENV_TENANT)
    if not raw:
        return None
    if not valid_slug(raw):
        from petastorm_tpu.obs.log import degradation

        degradation("tenant_label_invalid",
                    "%s=%r is not a bounded slug (%s) — running untagged",
                    ENV_TENANT, raw, _SLUG_RE.pattern)
        return None
    job = environ.get(ENV_JOB) or None
    priority = environ.get(ENV_PRIORITY) or None
    if job is not None and not valid_slug(job):
        from petastorm_tpu.obs.log import degradation

        degradation("tenant_label_invalid",
                    "%s=%r is not a bounded slug — dropping job id",
                    ENV_JOB, job)
        job = None
    if priority is not None and priority not in _PRIORITIES:
        from petastorm_tpu.obs.log import degradation

        degradation("tenant_label_invalid",
                    "%s=%r not in %r — dropping priority hint",
                    ENV_PRIORITY, priority, _PRIORITIES)
        priority = None
    return TenantContext(raw, job=job, priority=priority)


def resolve(tenant=None, env_default=True):
    """Resolution order: explicit argument > environment > None.

    ``tenant`` may be a :class:`TenantContext`, a bare slug string, or None.
    An invalid *explicit* value raises (the caller is present to fix it);
    the env path degrades instead (see :func:`from_env`).
    """
    if isinstance(tenant, TenantContext):
        return tenant
    if isinstance(tenant, str):
        return TenantContext(tenant)
    if tenant is not None:
        raise TypeError("tenant= must be a TenantContext, a slug string, or "
                        "None, not %r" % (tenant,))
    return from_env() if env_default else None


# ---------------------------------------------------------------------------
# current-context plumbing: a thread-local activation (worker threads, around
# each item) layered over a process default (children, via attach_from_env).

_tls = threading.local()
_process_default = None


def set_default(ctx):
    """Install ``ctx`` (or None) as the process-wide default tenant."""
    global _process_default
    _process_default = ctx


def attach_from_env():
    """Adopt the environment's tenant as the process default — the pool-child
    bootstrap hook (``_child_worker`` calls this beside the arena attach, so
    IO and span charges inside the child land on the parent's tenant)."""
    set_default(from_env())
    return _process_default


def current():
    """The active :class:`TenantContext` (thread activation, else process
    default), or None. The untagged fast path is two attribute reads."""
    ctx = getattr(_tls, "ctx", None)
    return ctx if ctx is not None else _process_default


def current_label():
    """The active tenant id, or None when untagged."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        ctx = _process_default
    return None if ctx is None else ctx.tenant


def label_of(ctx):
    """Render a context as its report/panel label (None => ``"-"``)."""
    return UNTAGGED if ctx is None else ctx.tenant


@contextmanager
def activate(ctx):
    """Thread-locally activate ``ctx`` for the duration (no-op for None —
    the surrounding default keeps applying)."""
    if ctx is None:
        yield
        return
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield
    finally:
        _tls.ctx = prev


# ---------------------------------------------------------------------------
# the meter: ptpu_tenant_* families charged at the resource sites.

#: resource key -> (metric family, unit note). The report and the stats panel
#: iterate this table, so adding a resource is one row.
RESOURCES = {
    "rows": ("ptpu_tenant_rows_total", "delivered rows"),
    "read_bytes": ("ptpu_tenant_read_bytes_total", "bytes read (all tiers)"),
    "decode_s": ("ptpu_tenant_decode_seconds_total", "decode seconds"),
    "worker_s": ("ptpu_tenant_worker_seconds_total", "worker item seconds"),
    "arena_byte_s": ("ptpu_tenant_arena_byte_seconds_total",
                     "arena residency (byte*seconds)"),
    "arena_bytes": ("ptpu_tenant_arena_resident_bytes",
                    "arena resident bytes (gauge)"),
    "hedged_gets": ("ptpu_tenant_hedged_gets_total", "hedged remote GETs"),
    "quarantined": ("ptpu_tenant_quarantined_rows_total", "quarantined rows"),
    "wire_bytes": ("ptpu_tenant_wire_bytes_total",
                   "transport frame bytes (tagged frames)"),
    "svc_items": ("ptpu_tenant_svc_items_total",
                  "data-service items served (ISSUE 19)"),
}


class _Meter:
    """Per-registry cache of tenant-labeled counters (building a counter is a
    dict lookup after the first charge — the tagged hot path stays cheap)."""

    __slots__ = ("_registry", "_counters", "_gauges", "_lock", "_arena")

    def __init__(self, registry):
        self._registry = registry
        self._counters = {}
        self._gauges = {}
        self._lock = threading.Lock()
        #: tenant label -> [resident_bytes, last_adjust_monotonic]; the
        #: byte*seconds integral accrues event-driven on every adjustment.
        self._arena = {}

    def counter(self, family, label):
        key = (family, label)
        c = self._counters.get(key)
        if c is None:
            with self._lock:
                c = self._counters.get(key)
                if c is None:
                    c = self._registry.counter(family, tenant=label)
                    self._counters[key] = c
        return c

    def gauge(self, family, label):
        key = (family, label)
        g = self._gauges.get(key)
        if g is None:
            with self._lock:
                g = self._gauges.get(key)
                if g is None:
                    g = self._registry.gauge(family, tenant=label)
                    self._gauges[key] = g
        return g

    def charge(self, resource, label, amount=1):
        if amount:
            self.counter(RESOURCES[resource][0], label).inc(amount)

    def arena_adjust(self, label, delta_bytes, now=None):
        """Account an arena residency change for ``label``: accrue the
        byte*seconds integral since the last adjustment, then move the
        resident-bytes gauge by ``delta_bytes`` (negative on evict/release).
        Event-driven integration: no sampler thread, exact between events."""
        now = time.monotonic() if now is None else now
        # metric building happens OUTSIDE the meter lock: counter() takes the
        # same (non-reentrant) lock on a cache miss, and the registry has its
        # own locks this meter must never nest under
        with self._lock:
            state = self._arena.get(label)
            if state is None:
                state = [0.0, now]
                self._arena[label] = state
            resident, last = state
            accrued = resident * (now - last) \
                if resident > 0 and now > last else 0.0
            state[0] = max(0.0, resident + delta_bytes)
            state[1] = now
            resident_now = state[0]
        if accrued:
            self.counter(RESOURCES["arena_byte_s"][0], label).inc(accrued)
        self.gauge(RESOURCES["arena_bytes"][0], label).set(resident_now)

    def arena_settle(self, now=None):
        """Flush the byte*seconds integral up to ``now`` for every tenant
        (report time: residency since the last event must still bill)."""
        now = time.monotonic() if now is None else now
        accrued = []
        with self._lock:
            for label, state in self._arena.items():
                resident, last = state
                if resident > 0 and now > last:
                    accrued.append((label, resident * (now - last)))
                state[1] = now
        for label, amount in accrued:
            self.counter(RESOURCES["arena_byte_s"][0], label).inc(amount)


_meters = {}
_meters_lock = threading.Lock()


def meter(registry=None):
    """The tenant meter bound to ``registry`` (default: the process default
    registry — where the io/arena families already live)."""
    if registry is None:
        from petastorm_tpu.obs.metrics import default_registry

        registry = default_registry()
    m = _meters.get(id(registry))
    if m is None:
        with _meters_lock:
            m = _meters.get(id(registry))
            if m is None:
                m = _Meter(registry)
                _meters[id(registry)] = m
    return m


def charge(resource, amount=1, label=None, registry=None):
    """Charge ``amount`` of ``resource`` to ``label`` (default: the current
    tenant). No-op when untagged — the untagged families stay the totals."""
    if label is None:
        label = current_label()
        if label is None:
            return
    meter(registry).charge(resource, label, amount)


# ---------------------------------------------------------------------------
# TenantUsageReport: the fleet-mergeable rollup.

_LABELED_RE = re.compile(r'^(?P<family>\w+)\{(?P<labels>[^}]*)\}$')
_TENANT_LABEL_RE = re.compile(r'(?:^|,)tenant="(?P<tenant>[^"]*)"')


def _tenant_of(full_name):
    """``(family, tenant)`` of a flat metric name carrying a tenant= label,
    else ``(None, None)``."""
    m = _LABELED_RE.match(full_name)
    if not m:
        return None, None
    t = _TENANT_LABEL_RE.search(m.group("labels"))
    if not t:
        return None, None
    return m.group("family"), t.group("tenant")


class TenantUsageReport:
    """Per-tenant resource rollup built from any flat metrics snapshot.

    Works identically on a live registry's :meth:`MetricsRegistry.snapshot`,
    a loaded export's ``metrics`` dict, or the summed ``totals`` of a fleet
    merge — counters sum per full labeled name in
    :func:`petastorm_tpu.obs.timeseries.merge_exports`, so per-tenant fleet
    totals come free. ``usage`` maps tenant label -> resource key -> value
    (resource keys from :data:`RESOURCES`).
    """

    __slots__ = ("usage",)

    def __init__(self, usage=None):
        self.usage = dict(usage or {})

    @classmethod
    def from_metrics(cls, metrics):
        family_to_resource = {fam: res
                              for res, (fam, _note) in RESOURCES.items()}
        usage = {}
        for name, value in metrics.items():
            family, tenant = _tenant_of(name)
            if family is None or not isinstance(value, (int, float)):
                continue
            resource = family_to_resource.get(family)
            if resource is None:
                continue
            usage.setdefault(tenant, {})[resource] = \
                usage.get(tenant, {}).get(resource, 0.0) + value
        return cls(usage)

    @classmethod
    def from_registry(cls, registry=None):
        if registry is None:
            from petastorm_tpu.obs.metrics import default_registry

            registry = default_registry()
        meter(registry).arena_settle()
        return cls.from_metrics(registry.snapshot())

    def tenants(self):
        return sorted(self.usage)

    def get(self, tenant, resource, default=0.0):
        return self.usage.get(tenant, {}).get(resource, default)

    def top_consumer(self, resource):
        """``(tenant, value)`` of the heaviest consumer of ``resource``
        (``(None, 0.0)`` when nothing is charged)."""
        best, best_v = None, 0.0
        for tenant in sorted(self.usage):
            v = self.usage[tenant].get(resource, 0.0)
            if v > best_v:
                best, best_v = tenant, v
        return best, best_v

    def merge(self, other):
        """Sum another report into a new one (fleet aggregation)."""
        usage = {t: dict(r) for t, r in self.usage.items()}
        for tenant, resources in other.usage.items():
            mine = usage.setdefault(tenant, {})
            for resource, value in resources.items():
                mine[resource] = mine.get(resource, 0.0) + value
        return TenantUsageReport(usage)

    def to_dict(self):
        return {t: dict(r) for t, r in sorted(self.usage.items())}

    def render(self, top=8):
        """The stats-panel table (a list of lines): tenants ranked by worker
        seconds (the scarcest shared resource), one row per tenant."""
        lines = ["tenants (ptpu_tenant_*):"]
        order = sorted(
            self.usage,
            key=lambda t: (-self.usage[t].get("worker_s", 0.0),
                           -self.usage[t].get("read_bytes", 0.0), t))
        for tenant in order[:top]:
            r = self.usage[tenant]
            lines.append(
                "  %-16s rows=%-8d read=%-9s worker=%6.2fs decode=%6.2fs  "
                "arena=%s*s  hedges=%d  quarantined=%d"
                % (tenant, int(r.get("rows", 0)),
                   "%.1fMB" % (r.get("read_bytes", 0.0) / 1e6),
                   r.get("worker_s", 0.0), r.get("decode_s", 0.0),
                   "%.1fMB" % (r.get("arena_byte_s", 0.0) / 1e6),
                   int(r.get("hedged_gets", 0)),
                   int(r.get("quarantined", 0))))
        if len(order) > top:
            lines.append("  ... and %d more tenants" % (len(order) - top))
        return lines

    def __repr__(self):
        return "TenantUsageReport(%d tenants)" % len(self.usage)


def _reset_for_tests():
    """Drop all meter/default state (test isolation)."""
    global _process_default
    _process_default = None
    if getattr(_tls, "ctx", None) is not None:
        _tls.ctx = None
    with _meters_lock:
        _meters.clear()
