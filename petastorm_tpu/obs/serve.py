"""Live metrics scrape endpoint: stdlib HTTP, opt-in, loopback by default
(ISSUE 12).

The file exporters (:mod:`petastorm_tpu.obs.export`) cover the sidecar-tail
pattern; a *fleet* needs pull: the disaggregated-service roadmap item scrapes
many hosts' pipelines, and ``petastorm-tpu-stats --merge`` aggregates what
this endpoint serves. :class:`MetricsServer` is a tiny stdlib
``ThreadingHTTPServer`` (no new dependencies, daemon threads, bounded
shutdown) exposing:

- ``GET /metrics`` — Prometheus text exposition (the standard scrape path);
- ``GET /timelines`` — the fleet-export JSON document
  (:func:`petastorm_tpu.obs.timeseries.export_document`): last snapshot +
  windowed time-series + the (wall, perf) clock anchor identifying this
  source — exactly what ``--merge`` consumes;
- ``GET /alerts`` — the attached SLO engine's alert list (empty without one);
- ``GET /healthz`` — liveness probe (200 + uptime JSON);
- plus any caller-provided ``routes``: ``{path: zero-arg callable}`` served
  as JSON per request (the data service mounts ``/fleet`` →
  :meth:`~petastorm_tpu.service.server.DataService.fleet_document` here).

**Security note:** the server binds ``127.0.0.1`` by default — metrics leak
dataset paths, host names and operational detail, so exposing them beyond the
host is an explicit opt-in (``host="0.0.0.0"``), to be fronted by whatever
authn the deployment already has. There is no TLS and no auth here by design:
this is a loopback/sidecar seam, not an internet-facing service.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

logger = logging.getLogger("petastorm_tpu.obs")


class MetricsServer:
    """Serve one registry's metrics + timelines over loopback HTTP.

    ``port=0`` (default) picks a free port — read it back from ``.port``
    after :meth:`start`. Use as a context manager around the serving loop::

        registry = MetricsRegistry()
        with MetricsServer(registry) as srv:
            print("scrape me at http://127.0.0.1:%d/metrics" % srv.port)
            ...

    The handler reads the registry/engine per request (pull model — zero cost
    when nobody scrapes), and request handling runs on daemon threads so a
    wedged scraper cannot block pipeline teardown.
    """

    def __init__(self, registry=None, host="127.0.0.1", port=0,
                 slo_engine=None, routes=None):
        from petastorm_tpu.obs.metrics import default_registry

        self._registry = registry or default_registry()
        self._slo_engine = slo_engine
        #: extra GET paths: {"/fleet": zero-arg callable -> JSON-able dict}
        self._routes = dict(routes or {})
        self._host = host
        self._requested_port = int(port)
        self._httpd = None
        self._thread = None
        self._started = time.time()
        self.port = None

    def start(self):
        if self._httpd is not None:
            return self
        server = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # stdlib default prints to stderr
                logger.debug("metrics-server: " + fmt, *args)

            def _send(self, body, content_type, status=200):
                if isinstance(body, str):
                    body = body.encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(server._registry.to_prometheus(),
                                   "text/plain; version=0.0.4")
                    elif path == "/timelines":
                        from petastorm_tpu.obs.timeseries import export_document

                        self._send(json.dumps(export_document(
                            server._registry)), "application/json")
                    elif path == "/alerts":
                        engine = server._slo_engine
                        alerts = [a.to_dict() for a in engine.alerts()] \
                            if engine is not None else []
                        self._send(json.dumps({"alerts": alerts}),
                                   "application/json")
                    elif path == "/healthz":
                        self._send(json.dumps(
                            {"ok": True,
                             "uptime_s": round(time.time() - server._started,
                                               3)}), "application/json")
                    elif path in server._routes:
                        self._send(json.dumps(server._routes[path]()),
                                   "application/json")
                    else:
                        self._send(json.dumps(
                            {"error": "unknown path %s" % path,
                             "paths": ["/metrics", "/timelines", "/alerts",
                                       "/healthz"]
                             + sorted(server._routes)}),
                            "application/json", status=404)
                except BrokenPipeError:
                    pass  # scraper went away mid-response: its problem
                except Exception as e:  # noqa: BLE001 — a render bug must 500, not kill the thread
                    try:
                        self._send(json.dumps({"error": str(e)}),
                                   "application/json", status=500)
                    except OSError:
                        pass

        httpd = ThreadingHTTPServer((self._host, self._requested_port),
                                    _Handler)
        httpd.daemon_threads = True
        self._httpd = httpd
        self.port = httpd.server_address[1]
        self._thread = threading.Thread(
            target=httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name="ptpu-metrics-server", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10)

    @property
    def url(self):
        return None if self.port is None \
            else "http://%s:%d" % (self._host, self.port)

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
