"""Flight recorder: a bounded ring of recent pipeline events, dumped on demand.

A deeply concurrent pipeline that *hangs* (rather than crashes) leaves no
evidence behind: the interesting decisions — which worker claimed which piece,
when a queue filled, which degradation fired — happened seconds before the
stall, and by the time an operator attaches a debugger the state is gone. The
:class:`FlightRecorder` keeps the last ``max_events`` structured events in a
lock-free bounded ring (``collections.deque`` appends are atomic under the
GIL — one append per event, no formatting until a dump), so the stall watchdog
(:mod:`petastorm_tpu.obs.health`), the crash hooks, or an on-demand
``DataLoader.health_report()`` can reconstruct the final seconds.

What rides in the ring (all opt-in — recording only happens when a health
monitor is attached): dispatch/steal decisions (``PullDispatcher``), pipeline
stage span edges from the loader producer, queue transitions (end-of-stream
sentinels, stop events), every degradation-log entry, and watchdog verdicts.

:func:`active_recorders` is the module-global hook the degradation log uses to
mirror its entries into whichever monitors are live, without the log module
depending on the health layer.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
import weakref
from collections import deque

#: recorders currently attached to a live HealthMonitor — the degradation log
#: mirrors entries into these (weak: a dead monitor stops receiving, silently)
_active_lock = threading.Lock()
_active = weakref.WeakSet()


def activate(recorder):
    with _active_lock:
        _active.add(recorder)


def deactivate(recorder):
    with _active_lock:
        _active.discard(recorder)


def active_recorders():
    """Snapshot of recorders attached to live monitors (possibly empty).
    Lock-free fast path when none are active: the degradation log calls this
    per occurrence, and with health disabled (the common case) it must not
    take a process-global lock on per-item paths."""
    if not _active:
        return ()
    with _active_lock:
        return list(_active)


class FlightRecorder:
    """Bounded ring of ``(t, kind, fields)`` events.

    ``record`` is the hot-path entry point: one tuple build plus one deque
    append (the deque's ``maxlen`` makes it a ring — old events fall off the
    far end). No lock on the append path; ``events()`` snapshots under the
    GIL's deque-iteration guarantees via ``list()``.
    """

    def __init__(self, max_events=2048):
        self._events = deque(maxlen=max(16, int(max_events)))
        self._origin = time.perf_counter()
        self._wall_origin = time.time()

    def record(self, kind, **fields):
        self._events.append((time.perf_counter(), kind, fields))

    def __len__(self):
        return len(self._events)

    def events(self):
        """Recent events as dicts, oldest first: ``{"t_s", "kind", ...fields}``
        with ``t_s`` relative to recorder creation."""
        return [{"t_s": round(t - self._origin, 6), "kind": kind, **fields}
                for t, kind, fields in list(self._events)]

    def clear(self):
        self._events.clear()


#: tmp-name disambiguator: two monitors in one process can share the default
#: pid-keyed flight_path and dump concurrently (e.g. a wedged filesystem
#: stalling train + eval loaders at once) — a pid-only tmp suffix would have
#: them truncating each other's half-written record
_tmp_seq = itertools.count()


def write_flight_record(path, record):
    """Atomically write one flight record as JSON (tmp + rename, like the
    Prometheus exporter: a reader never sees a torn file). Non-JSON values are
    stringified — a flight record must never fail to serialize at the exact
    moment it matters most. Returns ``path``."""
    tmp = "%s.tmp.%d.%d" % (path, os.getpid(), next(_tmp_seq))
    try:
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1, default=str)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return path
