"""Critical-path attribution: fold per-batch span DAGs into a step-time report.

Input is what :class:`petastorm_tpu.obs.provenance.ProvenanceRecorder` stores
per delivered batch: batch-plane spans (collate / queue put / decode / h2d)
plus the contributing items' spans (reader reads, readahead, remote GETs,
wire, transform, child work) on one clock-aligned timeline. The fold is the
standard flame-graph self-time rule — a span's **self time** is its duration
minus the time covered by spans strictly nested inside it — so nesting works
whatever the sites are named: a ``reader.read`` that spends most of its time
inside an ``io.remote`` span is charged the residual only, and a
``wire.roundtrip`` containing the child's ``child.work`` span is charged just
the wire overhead. Partially-overlapping siblings (a background readahead
read racing the current decode) are charged independently: overlap means the
time was NOT serialized behind the step, and the per-site totals say where
wall time went, not how it summed.

The :class:`AttributionReport` answers the question the stage histograms
cannot: *which site owns the critical path of my slow batches* — per-site
self seconds and shares, batch step-time percentiles split by cache tier and
degradation/quarantine cause, and a verdict line of the form "your p99 batch
spent 61% in io.remote" computed over the slowest decile. It refines the PR 3
``bottleneck_report()`` (which names a SIDE of the host queue) down to a
concrete site.
"""
from __future__ import annotations

import dataclasses


def fold_self_times(spans):
    """Per-site self time from possibly-nested spans of ONE logical chain.

    ``spans`` is ``[(site, t0, t1, pid)]``. Sorted by ``(t0, -t1)`` and folded
    with a stack: a span contained in the stack top is its child (its duration
    subtracts from the parent's self time); a span partially overlapping the
    top pops ONLY the top (a sibling, not a parent — enclosing ancestors that
    still contain the new span keep their parenthood). Returns
    ``{site: self_seconds}``.

    Feed this one item's (or one batch-plane's) spans at a time: two
    CONCURRENT items' timelines interleave, and folding them together would
    charge an outer span twice (once as itself, once through the overlapping
    peer that blocked its child subtraction) — :func:`analyze_batches` folds
    per record and sums."""
    out = {}
    stack = []  # [site, t0, t1, child_cover]
    for site, t0, t1, _pid in sorted(spans, key=lambda s: (s[1], -s[2])):
        dur = max(0.0, t1 - t0)
        while stack and stack[-1][2] <= t0:
            _flush(stack, out)  # fully before us: finished branch
        while stack and stack[-1][2] < t1:
            _flush(stack, out)  # ends mid-span: a sibling, never a parent
        if stack:
            stack[-1][3] += dur  # nested: cover the parent
        stack.append([site, t0, t1, 0.0])
    while stack:
        _flush(stack, out)
    return out


def _flush(stack, out):
    site, t0, t1, covered = stack.pop()
    self_s = max(0.0, (t1 - t0) - covered)
    out[site] = out.get(site, 0.0) + self_s


def diff_self_times(sites_a, sites_b, min_share=0.05):
    """Per-site self-time movement between two runs' ``stage_self_s`` maps
    (a = baseline, b = candidate): ``[(site, ratio, a_s, b_s)]`` sorted
    worst-growth-first. Only *significant* sites are compared — a site must
    own at least ``min_share`` of either run's total self time, so a
    0.1ms→0.4ms noise site cannot outrank a real 2× regression of the
    dominant seam. A site absent from the baseline is ratioed against a tiny
    epsilon floor of the baseline total (new work showing up IS a
    regression). Feeds ``petastorm-tpu-bench diff`` (ISSUE 12)."""
    total_a = sum(sites_a.values()) or 0.0
    total_b = sum(sites_b.values()) or 0.0
    floor = max(total_a, total_b) * 1e-3 + 1e-9
    out = []
    for site in set(sites_a) | set(sites_b):
        a = sites_a.get(site, 0.0)
        b = sites_b.get(site, 0.0)
        share_a = a / total_a if total_a else 0.0
        share_b = b / total_b if total_b else 0.0
        if max(share_a, share_b) < min_share:
            continue
        ratio = b / max(a, floor)
        out.append((site, ratio, a, b))
    out.sort(key=lambda e: -e[1])
    return out


def _percentile(sorted_values, q):
    if not sorted_values:
        return 0.0
    idx = min(len(sorted_values) - 1, int(q * len(sorted_values)))
    return sorted_values[idx]


@dataclasses.dataclass
class AttributionReport:
    """Step-time attribution over the recorded batch window."""

    batches: int
    #: per-site critical-path self seconds, summed over the window
    stage_self_s: dict
    #: per-site share of total critical-path self time (0..1)
    stage_share: dict
    #: site owning the largest critical-path share (None when idle)
    top_stage: str | None
    #: batch step-gap percentiles over the window (seconds)
    step_p50_s: float
    step_p99_s: float
    #: step-gap percentiles split by the batch's dominant cache tier
    by_tier: dict
    #: step-gap percentiles split by degradation/quarantine annotation
    by_cause: dict
    #: per-site share of self time within the SLOWEST decile of batches
    slow_share: dict
    #: the "your p99 batch spent 61% in io.remote" line
    verdict: str

    @property
    def slow_top(self):
        """The site owning the largest share of the SLOW-decile batches'
        critical path — the report's culprit (falls back to the overall top
        when no step gaps were recorded). This is what the bench harness
        asserts: an injected bottleneck inflates the slow batches, whatever
        one-off costs (child cold start, first-open footer reads) dominate
        the overall totals."""
        if self.slow_share:
            return max(self.slow_share, key=self.slow_share.get)
        return self.top_stage

    def to_dict(self):
        out = dataclasses.asdict(self)
        out["slow_top"] = self.slow_top
        return out

    def render(self):
        lines = ["attribution over %d batches (step p50 %.1fms, p99 %.1fms)"
                 % (self.batches, self.step_p50_s * 1e3, self.step_p99_s * 1e3),
                 "  %s" % self.verdict]
        total = sum(self.stage_self_s.values()) or 1.0
        for site in sorted(self.stage_self_s,
                           key=lambda s: -self.stage_self_s[s]):
            lines.append("  %-24s %9.3fs self  %5.1f%% of critical path"
                         % (site, self.stage_self_s[site],
                            100.0 * self.stage_self_s[site] / total))
        for label, split in (("cache tier", self.by_tier),
                             ("cause", self.by_cause)):
            for key in sorted(split):
                s = split[key]
                lines.append("  by %-10s %-12s %4d batches  p50 %8.1fms  "
                             "p99 %8.1fms"
                             % (label, key, s["batches"], s["p50_s"] * 1e3,
                                s["p99_s"] * 1e3))
        return "\n".join(lines)

    def __str__(self):
        return self.render()


def _batch_self_times(batch):
    """Per-site self seconds of one recorded batch view, folded PER RECORD
    (the batch-plane spans, then each contributing item's spans) and summed.
    Items run concurrently on different workers — folding their interleaved
    timelines together would double-charge outer spans, so each record's
    chain folds alone (cross-pid nesting WITHIN an item, like the child spans
    inside the driver's wire.roundtrip, is intended and preserved)."""
    totals = {}
    groups = [batch.get("spans", ())]
    groups.extend(rec.get("spans", ())
                  for rec in batch.get("item_records", ()))
    for group in groups:
        folded = fold_self_times(
            [(sp["site"], sp["t0"], sp["t1"], sp["pid"]) for sp in group])
        for site, sec in folded.items():
            totals[site] = totals.get(site, 0.0) + sec
    return totals


def _batch_tier(batch):
    """Dominant ``cache_tier`` annotation among the batch's items."""
    tiers = [rec.get("annotations", {}).get("cache_tier")
             for rec in batch.get("item_records", ())]
    tiers = [t for t in tiers if t]
    if not tiers:
        return None
    return max(set(tiers), key=tiers.count)


def _batch_causes(batch):
    causes = set()
    for rec in batch.get("item_records", ()):
        ann = rec.get("annotations", {})
        if ann.get("io_retries"):
            causes.add("io_retry")
        if ann.get("quarantined"):
            causes.add("quarantined")
        if ann.get("hedges"):
            causes.add("hedged")
        if rec.get("attempts", 1) > 1:
            causes.add("retried")
    return causes or {"clean"}


def analyze_batches(batch_views):
    """Fold recorded batch views (``ProvenanceRecorder.batches()``) into an
    :class:`AttributionReport`."""
    totals = {}
    gaps = []
    tier_gaps = {}
    cause_gaps = {}
    per_batch = []  # (gap, per-site self dict) for the slow-decile split
    for batch in batch_views:
        self_times = _batch_self_times(batch)
        for site, sec in self_times.items():
            totals[site] = totals.get(site, 0.0) + sec
        gap = batch.get("step_gap_s")
        if gap is not None:
            gaps.append(gap)
            per_batch.append((gap, self_times))
            tier = _batch_tier(batch)
            if tier:
                tier_gaps.setdefault(tier, []).append(gap)
            for cause in _batch_causes(batch):
                cause_gaps.setdefault(cause, []).append(gap)
    total_self = sum(totals.values())
    share = {site: (sec / total_self if total_self else 0.0)
             for site, sec in totals.items()}
    top = max(totals, key=totals.get) if totals else None
    gaps.sort()

    def split(groups):
        return {key: {"batches": len(vals),
                      "p50_s": round(_percentile(sorted(vals), 0.50), 6),
                      "p99_s": round(_percentile(sorted(vals), 0.99), 6)}
                for key, vals in groups.items()}

    # slow-decile attribution: where did the SLOWEST batches spend their path?
    slow_share = {}
    verdict = "not enough recorded batches to attribute"
    if per_batch:
        per_batch.sort(key=lambda e: e[0])
        slow = per_batch[max(0, int(0.9 * len(per_batch))):] or per_batch[-1:]
        slow_totals = {}
        for _gap, self_times in slow:
            for site, sec in self_times.items():
                slow_totals[site] = slow_totals.get(site, 0.0) + sec
        slow_sum = sum(slow_totals.values())
        if slow_sum > 0:
            slow_share = {site: sec / slow_sum
                          for site, sec in slow_totals.items()}
            slow_top = max(slow_share, key=slow_share.get)
            verdict = ("your p99 batch spent %d%% of its critical path in %s"
                       % (round(100 * slow_share[slow_top]), slow_top))
        elif top is not None:
            verdict = ("critical path dominated by %s (%d%% of self time)"
                       % (top, round(100 * share.get(top, 0.0))))
    elif top is not None:
        verdict = ("critical path dominated by %s (%d%% of self time)"
                   % (top, round(100 * share.get(top, 0.0))))
    return AttributionReport(
        batches=len(batch_views),
        stage_self_s={site: round(sec, 6) for site, sec in totals.items()},
        stage_share={site: round(f, 4) for site, f in share.items()},
        top_stage=top,
        step_p50_s=round(_percentile(gaps, 0.50), 6),
        step_p99_s=round(_percentile(gaps, 0.99), 6),
        by_tier=split(tier_gaps),
        by_cause=split(cause_gaps),
        slow_share={site: round(f, 4) for site, f in slow_share.items()},
        verdict=verdict,
    )
