"""Metric exporters: Prometheus text files and periodic JSONL snapshots.

Two pull points for the registry (:mod:`petastorm_tpu.obs.metrics`):

- :func:`write_prometheus` — one atomic write of the text exposition format
  (tmp + rename, so a scraping sidecar never reads a torn file);
- :class:`Reporter` — a daemon thread snapshotting every ``interval_s`` into a
  JSONL stream (one ``{"ts": ..., "metrics": {...}}`` object per line) and/or
  refreshing a Prometheus file. ``petastorm-tpu-stats`` pretty-prints either.

:func:`parse_prometheus_text` is the minimal parser the CI smoke step and the
test suite validate exports with (no prometheus_client dependency — the
container must not need a pip install to check its own output).
"""
from __future__ import annotations

import itertools
import json
import os
import re
import sys
import threading
import time

from petastorm_tpu.obs.metrics import default_registry

#: per-process tmp-name disambiguator: two THREADS writing the same target
#: concurrently (a Reporter plus a manual write) must not share a tmp file —
#: pid alone is not enough (itertools.count is atomic under the GIL)
_tmp_seq = itertools.count()


def write_prometheus(path, registry=None):
    """Atomically write ``registry.to_prometheus()`` to ``path``; returns path."""
    registry = registry or default_registry()
    text = registry.to_prometheus()
    tmp = "%s.tmp.%d.%d" % (path, os.getpid(), next(_tmp_seq))
    try:
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):  # replace failed: don't litter tmp files
            try:
                os.remove(tmp)
            except OSError:
                pass
    return path


_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?P<labels>\{[^}]*\})?\s+'
    r'(?P<value>[-+]?(?:[0-9.eE+-]+|Inf|NaN))\s*$')


def parse_prometheus_text(text):
    """Parse Prometheus text format into ``{name{labels}: float}`` + checks.

    Raises ``ValueError`` on any malformed line, on a sample whose family has
    no ``# TYPE`` header, and on histogram buckets whose cumulative counts
    decrease — the validations the CI stats-smoke step asserts.
    """
    samples = {}
    types = {}
    bucket_runs = {}  # (family, non-le labels) -> last cumulative count
    for lineno, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4 or parts[3] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"):
                raise ValueError("line %d: malformed TYPE: %r" % (lineno, line))
            types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError("line %d: malformed sample: %r" % (lineno, line))
        name = m.group("name")
        family = re.sub(r"_(bucket|sum|count)$", "", name)
        if name not in types and family not in types:
            raise ValueError("line %d: sample %r has no # TYPE header"
                             % (lineno, name))
        value = float(m.group("value"))
        labels = m.group("labels") or ""
        if name.endswith("_bucket"):
            key = (family, re.sub(r'le="[^"]*",?', "", labels))
            last = bucket_runs.get(key)
            if last is not None and value < last:
                raise ValueError(
                    "line %d: non-monotonic histogram bucket for %s"
                    % (lineno, family))
            bucket_runs[key] = value
        samples[name + labels] = value
    return samples


#: live Reporters flushed by the crash hooks (ISSUE 5 satellite): a run that
#: dies mid-interval — unhandled exception or plain interpreter exit — must
#: not lose its final JSONL/Prometheus window. start() registers, stop()
#: removes; the hooks themselves are installed once per process.
_live_lock = threading.Lock()
_live_reporters = []
_hooks_installed = False


def _flush_live_reporters():
    with _live_lock:
        reporters = list(_live_reporters)
    for reporter in reporters:
        try:
            reporter._write_once()
        except OSError:
            pass  # a dying process's disk may be the reason it is dying


def _install_exit_hooks():
    """atexit + sys.excepthook (chained), installed once per process."""
    global _hooks_installed
    with _live_lock:
        if _hooks_installed:
            return
        _hooks_installed = True
    import atexit

    atexit.register(_flush_live_reporters)
    previous = sys.excepthook

    def _flushing_excepthook(exc_type, exc, tb):
        _flush_live_reporters()
        previous(exc_type, exc, tb)

    sys.excepthook = _flushing_excepthook


class Reporter:
    """Background snapshot thread: JSONL stream and/or Prometheus file.

    Daemonized and stop-event driven (never blocks interpreter exit); one
    final snapshot is flushed on :meth:`stop` so short runs still leave a
    record — and, while the reporter is live, on interpreter exit and on an
    unhandled exception (atexit + a chained ``sys.excepthook``), so a run
    that dies mid-interval still leaves its final window on disk. Use as a
    context manager around the serving loop::

        with Reporter(jsonl_path="run_stats.jsonl", interval_s=2.0):
            for batch in loader: ...
        # petastorm-tpu-stats run_stats.jsonl   (live, from another terminal)
    """

    #: JSONL line schema (ISSUE 12 satellite): v2 lines carry a ``perf``
    #: stamp and the reporter's ``anchor`` (wall, perf, host, pid) so a
    #: cross-host ``petastorm-tpu-stats --merge`` places every window on the
    #: anchored timeline (wall trusted ONCE, elapsed measured on the perf
    #: clock — the PR 3/10 trace-merge scheme) instead of trusting each
    #: line's possibly-skewed/stepping wall stamp
    SCHEMA = "ptpu-stats-v2"

    def __init__(self, registry=None, interval_s=5.0, jsonl_path=None,
                 prom_path=None, max_bytes=None, keep=3, timelines=True):
        if jsonl_path is None and prom_path is None:
            raise ValueError("Reporter needs jsonl_path and/or prom_path")
        self._registry = registry or default_registry()
        self._interval_s = float(interval_s)
        self._jsonl_path = jsonl_path
        self._prom_path = prom_path
        #: feed the registry's windowed time-series on this cadence (ISSUE
        #: 12): one registry pass per flush on THIS thread — the hot paths
        #: never see the temporal plane. False opts out (a second Reporter
        #: tailing the same registry should not double-sample the windows).
        self._timelines = bool(timelines)
        import socket

        self._anchor = {"wall": time.time(), "perf": time.perf_counter(),
                        "host": socket.gethostname(), "pid": os.getpid()}
        #: size-capped rotation (ISSUE 10 satellite): when appending would
        #: grow the JSONL stream past ``max_bytes``, the file rotates to
        #: ``<path>.1`` (existing ``.1``→``.2``, …; at most ``keep`` rotated
        #: files retained) BEFORE the write — a multi-day run can no longer
        #: grow the sidecar unbounded. None (default) = never rotate. The
        #: atexit/crash flush goes through the same path, so the final window
        #: survives rotation too.
        self._max_bytes = None if max_bytes is None else int(max_bytes)
        self._keep = max(1, int(keep))
        self._stop_event = threading.Event()
        self._thread = None
        self._rotate_lock = threading.Lock()

    def _maybe_rotate(self, nbytes_next):
        """Rotate ``jsonl_path`` when the pending append would cross the cap.
        Serialized against the crash-hook flush (two writers, one shift
        chain); rotation failures degrade to appending in place — losing the
        cap beats losing the snapshot."""
        if self._max_bytes is None:
            return
        with self._rotate_lock:
            try:
                size = os.path.getsize(self._jsonl_path)
            except OSError:
                return  # nothing to rotate yet
            if size + nbytes_next <= self._max_bytes:
                return
            try:
                oldest = "%s.%d" % (self._jsonl_path, self._keep)
                if os.path.exists(oldest):
                    os.remove(oldest)
                for i in range(self._keep - 1, 0, -1):
                    src = "%s.%d" % (self._jsonl_path, i)
                    if os.path.exists(src):
                        os.replace(src, "%s.%d" % (self._jsonl_path, i + 1))
                os.replace(self._jsonl_path, self._jsonl_path + ".1")
            except OSError:
                pass  # degrade: append past the cap rather than drop data

    def _write_once(self):
        if self._timelines:
            # sample the windowed series on the reporter cadence; the SLO
            # engine (attached as a store listener) evaluates on the same
            # tick. Never lets a listener/sampling failure kill the flush.
            try:
                self._registry.sample_timelines()
            except Exception:  # noqa: BLE001 — flushing beats sampling
                from petastorm_tpu.obs.log import degradation

                degradation("timeline_sample_error",
                            "timeline sampling failed on the Reporter "
                            "cadence; snapshots continue without windows")
        if self._prom_path is not None:
            write_prometheus(self._prom_path, self._registry)
        if self._jsonl_path is not None:
            line = json.dumps({"schema": self.SCHEMA, "ts": time.time(),
                               "perf": time.perf_counter(),
                               "anchor": self._anchor,
                               "metrics": self._registry.snapshot()}) + "\n"
            self._maybe_rotate(len(line))
            with open(self._jsonl_path, "a") as f:
                f.write(line)

    def _run(self):
        while not self._stop_event.wait(self._interval_s):
            try:
                self._write_once()
            except OSError:
                pass  # a full/removed disk must not kill the reporter loop

    def start(self):
        self._stop_event.clear()
        _install_exit_hooks()
        with _live_lock:
            if self not in _live_reporters:
                _live_reporters.append(self)
        self._thread = threading.Thread(target=self._run, name="ptpu-obs-report",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop_event.set()
        with _live_lock:
            if self in _live_reporters:
                _live_reporters.remove(self)
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10)
        try:
            self._write_once()  # final snapshot: short runs leave a record too
        except OSError:
            pass

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()


def read_latest_jsonl_snapshot(path):
    """Last well-formed ``{"ts", "metrics"}`` object in a Reporter JSONL stream
    (tolerates a torn final line from a live writer); None when none exists."""
    recent = read_recent_jsonl_snapshots(path, limit=1)
    return recent[-1] if recent else None


def read_recent_jsonl_snapshots(path, limit=64):
    """Last ``limit`` well-formed snapshot objects, oldest first (the
    ``petastorm-tpu-stats --watch`` sparkline feed; tolerates torn lines)."""
    from collections import deque

    recent = deque(maxlen=max(1, int(limit)))
    with open(path, "r") as f:
        for line in f:
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict) and "metrics" in obj:
                recent.append(obj)
    return list(recent)
