"""Causal per-item provenance: which row group lost time where (ISSUE 10).

The obs stack so far sees **stages** (``ptpu_pipeline_stage_seconds``, health
heartbeats, bottleneck verdicts) but not **items**: when a p99 batch is slow,
nothing says whether it lost time to a remote GET tail, a quarantine retry, a
cache miss, or the wire. This module records one :class:`ItemProvenance` per
dispatched plan item — keyed by the stable ``"epoch=E ordinal=O path:rg"``
item key the chaos plane already uses — accumulating ``(site, t_start, t_end,
pid)`` spans and annotations (cache tier served from, hedges fired/won, retry
and quarantine attempts, degradation causes) as the item flows through the
existing seams:

- reader reads / coalesced runs (``reader.read`` / ``reader.read_run``),
- readahead-served tables (``io.readahead``) and remote ranged GETs
  (``io.remote``),
- the cache-tier funnel (annotation ``cache_tier`` = mem/disk/remote),
- transient-IO retries and poison-quarantine attempts,
- the declarative transform's fused stages (``transform`` /
  ``transform.<fused-label>``),
- the process-pool wire (``wire.slab_wait`` / ``wire.roundtrip`` /
  ``wire.decode``) — child-side spans cross the pool by piggybacking on the
  result header exactly like the PR 3 child-trace merge (clock-aligned through
  the child's wall/perf anchor pair),
- the loader's batch plane (``loader.collate`` / ``loader.host_queue_put`` /
  ``loader.decode`` / ``loader.h2d``).

Delivered batches are attributed to their contributing items through the
in-order delivery FIFO (non-shuffling loaders; shuffling decorrelates rows
from items, so batch membership is recorded as unknown there), exposed as
``DataLoader.batch_provenance()``; the critical-path analyzer
(:mod:`petastorm_tpu.obs.critical_path`) folds the per-batch span DAGs into a
step-time attribution report (``DataLoader.attribution_report()``).

Hot-path contract (the ``trace.py`` / chaos pattern): everything is a no-op
behind ``ACTIVE is None`` — one module-global check per site when disabled.
Pool children arm a lightweight :class:`_ChildCollector` at bootstrap (always:
the cost is a handful of ``perf_counter`` pairs per row-group item, noise next
to parquet IO — the same justification as the always-on child trace spans) and
the parent merges the piggybacked spans only when a recorder is attached.

One armed :class:`ProvenanceRecorder` per process at a time (like the chaos
plane's ``ACTIVE`` fault plan): a second ``arm()`` raises — give concurrent
provenance-enabled loaders their own processes, or share one recorder.
"""
from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
import zlib

from petastorm_tpu.chaos import item_key as _chaos_item_key

#: the armed collector for THIS process: a :class:`ProvenanceRecorder` in the
#: consumer process, a :class:`_ChildCollector` in pool children, or None
#: (disabled — every hook is one ``is None`` check)
ACTIVE = None

_PID = os.getpid()

_tls = threading.local()


def item_key(tagged_item):
    """The stable provenance key of a dispatched plan item — the SAME
    ``"epoch=E ordinal=O path:rg"`` string the chaos plane keys its fault
    rules by (single-sourced: :func:`petastorm_tpu.chaos.item_key`)."""
    return _chaos_item_key(tagged_item)


def item_identity(tagged_item):
    """``(epoch, ordinal, key)`` for a tagged plan item; ordinal pair falls
    back to the key string when the item is not the tagged 3-tuple shape."""
    key = _chaos_item_key(tagged_item)
    if isinstance(tagged_item, tuple) and len(tagged_item) == 3:
        return tagged_item[0], tagged_item[1], key
    return None, key, key


_item_identity = item_identity


class ItemProvenance:
    """One dispatched plan item's causal record: trace id, spans, annotations.

    Span times are ``perf_counter`` values on the OWNING recorder's timeline
    (child spans are clock-aligned into the parent recorder's timeline on
    absorption, the PR 3 trace-merge scheme). ``trace_id`` is a stable crc32
    of the item key — identical in every process that touches the item, which
    is what lets Perfetto flow events link one item's spans across pid lanes.
    """

    __slots__ = ("epoch", "ordinal", "key", "trace_id", "spans",
                 "annotations", "rows", "attempts")

    def __init__(self, epoch, ordinal, key):
        self.epoch = epoch
        self.ordinal = ordinal
        self.key = key
        self.trace_id = zlib.crc32(key.encode("utf-8", "replace")) & 0x7FFFFFFF
        self.spans = []       # [(site, t0, t1, pid)]
        self.annotations = {}
        self.rows = 0         # rows this item delivered to the consumer
        self.attempts = 1     # dispatch attempts observed (retries/respawns)

    def add_span(self, site, t0, t1, pid=None):
        self.spans.append((site, t0, t1, _PID if pid is None else pid))

    def annotate(self, name, value):
        self.annotations[name] = value

    def annotate_add(self, name, n=1):
        self.annotations[name] = self.annotations.get(name, 0) + n

    def to_dict(self):
        return {
            "key": self.key,
            "trace_id": self.trace_id,
            "epoch": self.epoch,
            "ordinal": self.ordinal,
            "rows": self.rows,
            "attempts": self.attempts,
            "annotations": dict(self.annotations),
            "spans": [{"site": s, "t0": t0, "t1": t1, "pid": pid}
                      for s, t0, t1, pid in self.spans],
        }


class BatchProvenance:
    """One delivered batch: its contributing items + batch-plane spans.

    ``items`` is ``[(epoch, ordinal, rows_from_that_item)]`` consumed from the
    delivery FIFO (``None`` when membership is unknowable — shuffling buffers
    decorrelate rows from row groups). ``delivered_t``/``step_gap_s`` are
    stamped when the consumer takes the batch; the gap to the PREVIOUS
    delivery is the step-time denominator the attribution report splits."""

    __slots__ = ("seq", "rows", "items", "spans", "created_t", "delivered_t",
                 "step_gap_s", "dropped")

    def __init__(self, seq, rows, items):
        self.seq = seq
        self.rows = rows
        self.items = items
        self.spans = []  # batch-plane spans [(site, t0, t1, pid)]
        self.created_t = time.perf_counter()
        self.delivered_t = None
        self.step_gap_s = None
        self.dropped = False

    def add_span(self, site, t0, t1):
        self.spans.append((site, t0, t1, _PID))

    def to_dict(self):
        return {
            "seq": self.seq,
            "rows": self.rows,
            "items": None if self.items is None
            else [list(entry) for entry in self.items],
            "step_gap_s": self.step_gap_s,
            "spans": [{"site": s, "t0": t0, "t1": t1, "pid": pid}
                      for s, t0, t1, pid in self.spans],
        }


# --------------------------------------------------------------------------------------
# module-level hooks (the hot-path surface: one `ACTIVE is None` check each)
# --------------------------------------------------------------------------------------


def current():
    """The :class:`ItemProvenance` the calling thread is working, or None."""
    return getattr(_tls, "item", None)


def begin_item(tagged_item):
    """Arm the calling thread's item context (executor worker loops / pool
    children call this around ``worker(item)``). Re-begins of the same
    ``(epoch, ordinal)`` (poison retries, respawn re-dispatch) reuse the
    existing record and bump its attempt count. MUST be paired with
    :func:`end_item` in a ``finally`` (graftlint GL-O003 enforces it)."""
    if ACTIVE is None:
        return None
    rec = ACTIVE.open_item(tagged_item)
    _tls.item = rec
    # stamp the tenant (ISSUE 18) as a plain annotation: it rides the child
    # piggyback blob and absorb_child's annotation merge unchanged, so child
    # spans land in the right tenant with zero new wire format
    from petastorm_tpu.obs import tenant as _tenant_ctx

    label = _tenant_ctx.current_label()
    if label is not None and "tenant" not in rec.annotations:
        rec.annotations["tenant"] = label
    return rec


def end_item():
    """Close the calling thread's item context; returns whatever the armed
    collector's ``close_item`` returns (the child collector returns the
    piggyback blob, the parent recorder returns None)."""
    if ACTIVE is None:
        return None
    rec = getattr(_tls, "item", None)
    _tls.item = None
    if rec is None:
        return None
    return ACTIVE.close_item(rec)


def add_span(site, t0, dur):
    """Record one span against the calling thread's current item (no-op when
    provenance is off or no item context is armed)."""
    if ACTIVE is None:
        return
    rec = getattr(_tls, "item", None)
    if rec is not None:
        rec.add_span(site, t0, t0 + dur)


@contextlib.contextmanager
def span(site):
    """Context manager recording the enclosed block as one item span."""
    if ACTIVE is None or getattr(_tls, "item", None) is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        add_span(site, t0, time.perf_counter() - t0)


def annotate(name, value):
    """Set an annotation on the current item (cache tier, degradation cause)."""
    if ACTIVE is None:
        return
    rec = getattr(_tls, "item", None)
    if rec is not None:
        rec.annotate(name, value)


def annotate_add(name, n=1):
    """Accumulate a numeric annotation (retries, hedges) on the current item."""
    if ACTIVE is None:
        return
    rec = getattr(_tls, "item", None)
    if rec is not None:
        rec.annotate_add(name, n)


def open_span(site):
    """Explicit open/close span handle for sites where a ``with`` block cannot
    bracket the region (split across control flow). The returned handle's
    ``close()`` records the span; close it in a ``finally`` — GL-O003 flags a
    handle opened without a finally-guarded close."""
    return _SpanHandle(site)


class _SpanHandle:
    __slots__ = ("site", "t0", "_closed", "_rec")

    def __init__(self, site):
        self.site = site
        self.t0 = time.perf_counter()
        self._closed = False
        # bind the record at OPEN time: the close may run after end_item()
        # cleared the thread-local (teardown paths)
        self._rec = current() if ACTIVE is not None else None

    def close(self):
        if self._closed:
            return
        self._closed = True
        rec = self._rec
        if rec is not None:
            rec.add_span(self.site, self.t0, time.perf_counter())


# --------------------------------------------------------------------------------------
# child-side collector (pool children: record, piggyback, forget)
# --------------------------------------------------------------------------------------


class _ChildCollector:
    """Minimal per-item collector for pool children: the record lives only
    until :func:`end_item` hands it back as the result-header piggyback blob
    ``(epoch, ordinal, spans, annotations)`` — spans on THIS process's
    ``perf_counter`` timeline; the parent aligns them through the child's
    wall/perf anchor pair (the same anchors the trace piggyback ships)."""

    def open_item(self, tagged_item):
        epoch, ordinal, key = _item_identity(tagged_item)
        return ItemProvenance(epoch, ordinal, key)

    def close_item(self, rec):
        if not rec.spans and not rec.annotations:
            return None
        return (rec.epoch, rec.ordinal, rec.key, list(rec.spans),
                dict(rec.annotations))


def arm_child():
    """Arm the lightweight child collector (pool-child bootstrap). Idempotent;
    never replaces an already-armed parent recorder (in-process executors)."""
    global ACTIVE
    if ACTIVE is None:
        ACTIVE = _ChildCollector()
    return ACTIVE


def child_collector():
    """A PRIVATE per-item collector for cross-wire producers (ISSUE 20: the
    data service's decode workers). Same record/piggyback/forget contract as
    :func:`arm_child`, but owned by the caller instead of installed as the
    process-global ``ACTIVE``: a :class:`DecodeWorker` co-hosted with a
    trainer thread (tests, single-host fleets) must record its ``svc.decode``
    spans without hijacking the trainer's hook dispatch — and a dedicated
    worker process gets the identical code path."""
    return _ChildCollector()


# --------------------------------------------------------------------------------------
# parent-side recorder
# --------------------------------------------------------------------------------------


class ProvenanceRecorder:
    """Process-wide provenance collector: item registry + batch attribution.

    ``max_items``/``max_batches`` bound memory on long runs (oldest evicted —
    the attribution window is the recent one being debugged). All methods are
    thread-safe: the reader's executor threads, the loader's producer and
    transfer threads, and the consumer all feed one recorder.
    """

    def __init__(self, max_items=8192, max_batches=2048):
        self._lock = threading.RLock()
        self._origin = time.perf_counter()
        self._wall_origin = time.time()
        self._max_items = int(max_items)
        self._max_batches = int(max_batches)
        self._items = collections.OrderedDict()  # (epoch, ordinal) -> record
        self._delivery_fifo = collections.deque()  # [epoch, ordinal, rows left]
        self._pending_transfer = collections.deque()
        self._pending_delivery = collections.deque()
        self._completed = collections.deque(maxlen=self._max_batches)
        self._current_transfer = None
        self._batch_seq = 0
        self._last_delivered_t = None
        self._quarantined = []  # [(epoch, ordinal, attempts, kind)]
        self._track_batches = True
        self._tracer = None  # optional TraceRecorder for Perfetto flow events
        self.duplicate_absorbs = 0  # same-item child blobs merged twice
        #: set by resolve() on recorders IT constructed: the owning
        #: reader/loader disarms at teardown; caller-supplied recorders stay
        #: armed (the caller owns the lifecycle)
        self._auto_disarm = False
        self._summary_cache = None  # (version key, summary dict)

    # -- arming -------------------------------------------------------------------------

    def arm(self):
        """Install this recorder as the process's ``ACTIVE`` collector (worker
        threads' ``begin_item``/``span`` hooks feed it). One recorder per
        process: a second concurrent ``arm()`` raises."""
        global ACTIVE
        with self._lock:
            if ACTIVE is self:
                return self
            if ACTIVE is not None and not isinstance(ACTIVE, _ChildCollector):
                raise RuntimeError(
                    "another ProvenanceRecorder is already armed in this "
                    "process — run one provenance-enabled loader per process, "
                    "or share its recorder")
            ACTIVE = self
        return self

    def disarm(self):
        global ACTIVE
        if ACTIVE is self:
            ACTIVE = None

    def set_trace(self, tracer):
        """Attach a :class:`petastorm_tpu.trace.TraceRecorder`: each finalized
        batch emits Perfetto flow events linking its items' spans across pid
        lanes in the trace dump."""
        with self._lock:
            self._tracer = tracer

    def set_batch_tracking(self, enabled):
        """Batch↔item attribution toggle: the loader disables it under
        shuffling (rows decorrelate from row groups there), and the delivery
        FIFO stays empty instead of growing unconsumed."""
        with self._lock:
            self._track_batches = bool(enabled)
            if not enabled:
                self._delivery_fifo.clear()

    # -- item plane ---------------------------------------------------------------------

    def open_item(self, tagged_item):
        epoch, ordinal, key = _item_identity(tagged_item)
        with self._lock:
            rec = self._items.get((epoch, ordinal))
            if rec is not None and rec.key == key:
                rec.attempts += 1  # retry/re-dispatch of the same item
                return rec
            rec = ItemProvenance(epoch, ordinal, key)
            self._store(rec)
        return rec

    def close_item(self, rec):
        # the record was registered at open; nothing to hand back parent-side
        return None

    def _store(self, rec):
        items = self._items
        items[(rec.epoch, rec.ordinal)] = rec
        while len(items) > self._max_items:
            items.popitem(last=False)

    def _get_or_create(self, epoch, ordinal, key=None):
        rec = self._items.get((epoch, ordinal))
        if rec is None:
            rec = ItemProvenance(epoch, ordinal,
                                 key or "epoch=%s ordinal=%s ?" % (epoch, ordinal))
            self._store(rec)
        elif key is not None and rec.key.endswith(" ?"):
            # a placeholder record (created by an out-of-order delivery note)
            # learns its full path:rg identity — trace id follows the key
            rec.key = key
            rec.trace_id = zlib.crc32(key.encode("utf-8", "replace")) \
                & 0x7FFFFFFF
        return rec

    def add_item_span(self, epoch, ordinal, site, t0, t1, key=None):
        """Driver-side span keyed by item identity (the pool driver threads
        record wire spans here — they never hold the item's thread context)."""
        with self._lock:
            self._get_or_create(epoch, ordinal, key).add_span(site, t0, t1)

    def annotate_item(self, epoch, ordinal, name, value, key=None):
        with self._lock:
            self._get_or_create(epoch, ordinal, key).annotate(name, value)

    def absorb_child(self, blob, pid, wall_anchor, perf_anchor):
        """Merge a pool child's piggybacked item record, clock-aligning its
        spans onto this recorder's timeline exactly like
        :meth:`petastorm_tpu.trace.TraceRecorder.add_child` (same host, shared
        wall clock; alignment error is wall-sampling jitter)."""
        if blob is None:
            return
        epoch, ordinal, key, spans, annotations = blob
        base = (wall_anchor - self._wall_origin) - perf_anchor + self._origin
        with self._lock:
            rec = self._items.get((epoch, ordinal))
            if rec is None:
                rec = self._get_or_create(epoch, ordinal, key)
            elif rec.key.endswith(" ?"):
                self._get_or_create(epoch, ordinal, key)  # learn the identity
            if any(p == pid for _s, _t0, _t1, p in rec.spans):
                # a retry re-delivered the same item from the same child:
                # count it, keep the fresh attempt's spans (the delivered one)
                self.duplicate_absorbs += 1
                rec.spans = [sp for sp in rec.spans if sp[3] != pid]
                rec.attempts += 1
            for site, t0, t1, span_pid in spans:
                # span_pid is the child's own pid (stamped at record time)
                rec.spans.append((site, t0 + base, t1 + base, span_pid or pid))
            for name, value in annotations.items():
                if isinstance(value, (int, float)) and name in rec.annotations:
                    rec.annotations[name] = rec.annotations[name] + value
                else:
                    rec.annotations[name] = value

    def note_quarantined(self, epoch, ordinal, attempts, kind):
        """Quarantine accounting (exactly-once beside delivery: a quarantined
        item never enters the delivery FIFO)."""
        with self._lock:
            rec = self._get_or_create(epoch, ordinal)
            rec.annotate("quarantined", kind)
            rec.attempts = max(rec.attempts, attempts)
            self._quarantined.append((epoch, ordinal, attempts, kind))

    def note_delivery(self, epoch, ordinal, rows):
        """Reader-side: ``rows`` of item ``(epoch, ordinal)`` entered the
        consumer stream (in order) — the batch cutter consumes this FIFO to
        attribute batches to items."""
        with self._lock:
            rec = self._get_or_create(epoch, ordinal)
            rec.rows += int(rows)
            if self._track_batches:
                self._delivery_fifo.append([epoch, ordinal, int(rows)])

    # -- batch plane ----------------------------------------------------------------

    def producer_cut(self, rows, collate_t0=None, collate_s=None):
        """A batch of ``rows`` was cut by the host batcher: attribute its
        membership from the delivery FIFO and open its
        :class:`BatchProvenance` (returned for the loader's later span/drop
        calls)."""
        with self._lock:
            items = None
            if self._track_batches:
                items = []
                need = int(rows)
                fifo = self._delivery_fifo
                while need > 0 and fifo:
                    entry = fifo[0]
                    take = min(entry[2], need)
                    items.append((entry[0], entry[1], take))
                    entry[2] -= take
                    need -= take
                    if entry[2] <= 0:
                        fifo.popleft()
            self._batch_seq += 1
            bp = BatchProvenance(self._batch_seq, int(rows), items)
            if collate_t0 is not None and collate_s:
                bp.add_span("loader.collate", collate_t0,
                            collate_t0 + collate_s)
            self._pending_transfer.append(bp)
            self._pending_delivery.append(bp)
        return bp

    def batch_dropped(self, bp):
        """A cut batch died inside the pipeline (short tail dropped, stopped
        delivery): retire it so the transfer/delivery pointers stay aligned."""
        with self._lock:
            bp.dropped = True
            try:
                self._pending_transfer.remove(bp)
            except ValueError:
                pass
            try:
                self._pending_delivery.remove(bp)
            except ValueError:
                pass

    def batch_span(self, bp, site, t0, dur):
        """Record a batch-plane span on a specific open batch."""
        if bp is not None and dur is not None:
            bp.add_span(site, t0, t0 + dur)

    def transfer_next(self):
        """The transfer thread is starting the next batch (strict FIFO order
        through the host queue): returns its BatchProvenance."""
        with self._lock:
            self._current_transfer = (self._pending_transfer.popleft()
                                      if self._pending_transfer else None)
            return self._current_transfer

    def transfer_span(self, site, t0, dur):
        """Record a span against the batch currently in transfer."""
        bp = self._current_transfer
        if bp is not None:
            bp.add_span(site, t0, t0 + dur)

    def batch_delivered(self):
        """The consumer took the next batch: finalize its provenance (stamp
        the delivery time and the step gap to the previous one), emit flow
        events when a tracer is attached, and return it."""
        now = time.perf_counter()
        with self._lock:
            if not self._pending_delivery:
                return None
            bp = self._pending_delivery.popleft()
            try:
                # host-only delivery paths never run a transfer stage: keep
                # the transfer pointer from trailing ever further behind
                self._pending_transfer.remove(bp)
            except ValueError:
                pass
            bp.delivered_t = now
            if self._last_delivered_t is not None:
                bp.step_gap_s = now - self._last_delivered_t
            self._last_delivered_t = now
            self._completed.append(bp)
            tracer = self._tracer
            records = None
            if tracer is not None and bp.items:
                records = [self._items.get((e, o)) for e, o, _r in bp.items]
        if tracer is not None and records:
            self._emit_flows(tracer, bp, [r for r in records if r is not None])
        return bp

    def _emit_flows(self, tracer, bp, records):
        """Perfetto flow events: one flow per item (id = the stable trace_id),
        stepping through the item's spans on their pid lanes and terminating
        at the batch's delivery on the local loader lane."""
        local = _PID
        add_point = getattr(tracer, "add_flow_point", None)
        if add_point is None:
            return
        for rec in records:
            points = sorted(rec.spans, key=lambda sp: sp[1])
            if not points:
                continue
            for site, t0, _t1, pid in points:
                lane = "ptpu-prov" if pid == local else "ptpu-child-%d" % pid
                add_point(rec.trace_id, lane, pid, t0, name=site)
            add_point(rec.trace_id, "ptpu-prov", local, bp.delivered_t,
                      name="batch.delivered", terminate=True)

    # -- reporting ----------------------------------------------------------------------

    def last_batch(self):
        """The most recently delivered batch's provenance view (dict with the
        contributing item records resolved), or None."""
        with self._lock:
            if not self._completed:
                return None
            bp = self._completed[-1]
            return self._batch_view(bp)

    def _batch_view(self, bp):
        out = bp.to_dict()
        items = []
        if bp.items:
            for epoch, ordinal, rows in bp.items:
                rec = self._items.get((epoch, ordinal))
                if rec is not None:
                    d = rec.to_dict()
                    d["rows_in_batch"] = rows
                    items.append(d)
        out["item_records"] = items
        return out

    def batches(self):
        """Snapshot of completed batch provenance records (newest last)."""
        with self._lock:
            return [self._batch_view(bp) for bp in self._completed]

    def items(self):
        """Snapshot of the item registry: ``{key: record dict}``."""
        with self._lock:
            return {rec.key: rec.to_dict() for rec in self._items.values()}

    def quarantined(self):
        with self._lock:
            return list(self._quarantined)

    def report(self, tenant=None):
        """Fold the completed batches into a step-time
        :class:`~petastorm_tpu.obs.critical_path.AttributionReport`.
        ``tenant`` (ISSUE 18) restricts the fold to batches whose
        contributing items carry that tenant annotation — "whose tail is
        this" becomes a per-tenant question."""
        from petastorm_tpu.obs.critical_path import analyze_batches

        batches = self.batches()
        if tenant is not None:
            batches = [b for b in batches if any(
                (item.get("annotations") or {}).get("tenant") == tenant
                for item in b.get("item_records") or ())]
        return analyze_batches(batches)

    def attribution_report(self, tenant=None):
        """Alias of :meth:`report` under the loader's public name, so a bare
        recorder answers ``attribution_report(tenant=...)`` the same way
        ``DataLoader.attribution_report`` does."""
        return self.report(tenant=tenant)

    def summary(self):
        """Flat numeric summary for the flight recorder and the metrics
        collector (``ptpu_prov_*`` families): counts plus per-site
        critical-path self seconds (site names sanitized to metric-safe
        suffixes). Memoized on the recorder's version (batches finalized /
        items seen): metric snapshots poll this on a cadence, and re-folding
        an unchanged 2k-batch window every few seconds would make the
        observability plane the thing the observability plane flags."""
        with self._lock:
            version = (self._batch_seq, len(self._completed),
                       len(self._items), len(self._quarantined),
                       self.duplicate_absorbs)
            cached = self._summary_cache
            if cached is not None and cached[0] == version:
                return dict(cached[1])
        report = self.report()
        with self._lock:
            out = {
                "items": len(self._items),
                "batches": len(self._completed),
                "quarantined": len(self._quarantined),
                "duplicate_absorbs": self.duplicate_absorbs,
            }
            for site, seconds in report.stage_self_s.items():
                out["self_s_%s" % _metric_safe(site)] = round(seconds, 6)
            self._summary_cache = (version, dict(out))
        return out


def _metric_safe(site):
    return "".join(c if c.isalnum() else "_" for c in site)


def env_enabled():
    """The ``PTPU_PROVENANCE`` no-code-change switch (mirrors ``PTPU_HEALTH``)
    — ONE copy of the accepted truthiness set."""
    return os.environ.get("PTPU_PROVENANCE", "") not in ("", "0", "false",
                                                         "no")


def resolve(provenance, env_default=True):
    """Normalize a ``provenance=`` argument (None/True/recorder) into an
    ARMED :class:`ProvenanceRecorder` or None. ``PTPU_PROVENANCE=1`` enables
    the default recorder when the argument is None (and ``env_default``).

    A recorder CONSTRUCTED here is tagged ``_auto_disarm``: the component it
    was built for (reader/loader) disarms it at ITS teardown. A recorder the
    caller passed in stays armed across teardowns — the caller owns its
    lifecycle (it may feed several pipelines in sequence)."""
    if provenance is None and env_default and env_enabled():
        provenance = True
    if not provenance:
        return None
    if isinstance(provenance, ProvenanceRecorder):
        rec = provenance
    else:
        rec = ProvenanceRecorder()
        rec._auto_disarm = True
    rec.arm()
    return rec
