"""``petastorm-tpu-stats``: live terminal dashboard for a run's metrics.

Reads what a :class:`petastorm_tpu.obs.export.Reporter` writes — a JSONL
snapshot stream (last line wins, so it works against a file another process is
appending to) or a Prometheus text file — and renders one dashboard frame:
stage latency percentiles, queue depths, heartbeat ages (with stalled actors
flagged), per-worker latencies, degradation counts, and the bottleneck
analyzer's verdict (``straggler`` included when per-worker data is present).

    petastorm-tpu-stats run_stats.jsonl            # one frame
    petastorm-tpu-stats --watch run_stats.jsonl    # redraw every 2s
    petastorm-tpu-stats --watch 0.5 metrics.prom   # redraw every 0.5s
    petastorm-tpu-stats --watch --once stats.jsonl # render ONE watch frame (CI)

Exit codes: 0 printed a snapshot, 1 no snapshot found / unreadable file.
"""
from __future__ import annotations

import argparse
import os
import re
import sys
import time


def _load_snapshot(path):
    """{metric full name: number-or-histogram-summary} from either format."""
    from petastorm_tpu.obs.export import (
        parse_prometheus_text,
        read_latest_jsonl_snapshot,
    )

    with open(path, "r") as f:
        head = f.read(1)
    if head == "{":  # Reporter JSONL stream
        obj = read_latest_jsonl_snapshot(path)
        return None if obj is None else obj["metrics"]
    with open(path, "r") as f:
        return _fold_prom_histograms(parse_prometheus_text(f.read()))


_BUCKET_RE = re.compile(r"^(?P<name>\w+)_bucket(?P<labels>\{.*\})$")


def _fold_prom_histograms(samples):
    """Collapse Prometheus ``_bucket``/``_sum``/``_count`` sample triplets into
    the same summary-dict shape JSONL snapshots carry (count/sum/mean/p50/p90/
    p99), so the renderer has ONE histogram representation."""
    out = {}
    hists = {}  # base full name (labels minus le) -> [(upper, cumulative)]
    for name, value in samples.items():
        m = _BUCKET_RE.match(name)
        if m:
            # anchor `le` as a whole label name: an unanchored match would
            # also hit inside labels merely ENDING in le (handle=, role=)
            labels = re.sub(r',le="[^"]*"|(?<=\{)le="[^"]*",?', "",
                            m.group("labels"))
            labels = "" if labels == "{}" else labels
            base = m.group("name") + labels
            le = re.search(r'(?<=[{,])le="([^"]*)"', name).group(1)
            upper = float("inf") if le == "+Inf" else float(le)
            hists.setdefault(base, []).append((upper, value))
            continue
        out[name] = value
    for base, buckets in hists.items():
        count = out.pop(base + "_count", None)
        total = out.pop(base + "_sum", 0.0)
        # reconstruct labeled _count/_sum keys too (labels ride on base)
        if count is None:
            bare = re.match(r"^(\w+)(\{.*\})?$", base)
            count = out.pop("%s_count%s" % (bare.group(1), bare.group(2) or ""),
                            None)
            total = out.pop("%s_sum%s" % (bare.group(1), bare.group(2) or ""),
                            0.0)
        buckets.sort()
        if count is None:
            count = buckets[-1][1] if buckets else 0
        summary = {"count": int(count), "sum": total,
                   "mean": (total / count) if count else 0.0}
        for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
            target = q * count
            val = 0.0
            prev_finite = 0.0
            if count:
                for upper, cum in buckets:
                    if cum >= target:
                        # +Inf bucket matches: report the last finite bound
                        val = prev_finite if upper == float("inf") else upper
                        break
                    if upper != float("inf"):
                        prev_finite = upper
            summary[key] = val
        out[base] = summary
    return out


def _labeled(metrics, family):
    """``{label value: metric value}`` for one single-label family."""
    out = {}
    prefix = family + "{"
    for name, value in metrics.items():
        if name.startswith(prefix):
            m = re.search(r'="([^"]*)"', name)
            if m:
                out[m.group(1)] = value
    return out


def _pipeline_stats_from(metrics):
    """Reconstruct a ``PipelineStats.snapshot()``-shaped dict from the exported
    ``ptpu_pipeline_*`` families (None when the run exported none)."""
    prefix = "ptpu_pipeline_"
    snap = {}
    for name, value in metrics.items():
        if name.startswith(prefix) and "{" not in name \
                and isinstance(value, (int, float)):
            snap[name[len(prefix):]] = value
    return snap or None


def _fmt_ms(v):
    return "%8.2f" % (v * 1e3)


def render_dashboard(metrics, title=""):
    """One dashboard frame (a plain string — the CLI prints it, tests assert
    on it). Sections appear only when their families are present, so the same
    renderer serves a bare-metrics run and a full health-enabled one."""
    lines = []
    if title:
        lines.append(title)
        lines.append("=" * min(78, max(20, len(title))))

    snap = _pipeline_stats_from(metrics)
    worker_lat = _labeled(metrics, "ptpu_worker_item_seconds")
    worker_lat = {k: v for k, v in worker_lat.items() if isinstance(v, dict)}

    # -- verdict first: the one line an operator reads under pager pressure
    # (computed ONCE per frame; the utilization detail rides right below it)
    if snap is not None and snap.get("batches"):
        from petastorm_tpu.obs.analyze import analyze_snapshot

        report = analyze_snapshot(snap, worker_latency=worker_lat or None)
        lines.append("verdict: %s" % report.verdict)
        lines.append("  %s" % report.reason)
        if report.utilization:
            lines.append("  utilization: " + "  ".join(
                "%s %.0f%%" % (side, 100 * report.utilization[side])
                for side in sorted(report.utilization)))

    # -- pipeline counters + queue depths
    if snap is not None:
        lines.append("pipeline: rows=%d batches=%d  host_queue=%d  "
                     "device_queue=%d"
                     % (snap.get("rows", 0), snap.get("batches", 0),
                        snap.get("host_queue_depth", 0),
                        snap.get("device_queue_depth", 0)))

    # -- stage latency percentiles
    stages = _labeled(metrics, "ptpu_pipeline_stage_seconds")
    stages = {k: v for k, v in stages.items() if isinstance(v, dict)}
    if stages:
        lines.append("stage latencies (ms):   %8s %8s %8s %8s"
                     % ("p50", "p90", "p99", "count"))
        for stage in sorted(stages):
            s = stages[stage]
            lines.append("  %-20s %s %s %s %8d"
                         % (stage, _fmt_ms(s.get("p50", 0)),
                            _fmt_ms(s.get("p90", 0)), _fmt_ms(s.get("p99", 0)),
                            s.get("count", 0)))

    # -- per-worker latency (straggler fodder)
    if worker_lat:
        from petastorm_tpu.obs.analyze import detect_straggler

        straggler = detect_straggler(worker_lat)
        parts = []
        for w in sorted(worker_lat):
            s = worker_lat[w]
            flag = " [STRAGGLER]" if straggler \
                and straggler["worker"] == str(w) else ""
            parts.append("w%s %.1fms×%d%s"
                         % (w, s.get("mean", 0) * 1e3, s.get("count", 0), flag))
        lines.append("workers: " + "  ".join(parts))

    # -- heartbeats (the health layer's export)
    ages = {name[len("ptpu_health_hb_age_s_"):]: v
            for name, v in metrics.items()
            if name.startswith("ptpu_health_hb_age_s_")}
    if ages:
        stalled = {name[len("ptpu_health_hb_stalled_"):]: v
                   for name, v in metrics.items()
                   if name.startswith("ptpu_health_hb_stalled_")}
        parts = []
        for actor in sorted(ages, key=lambda a: -ages[a]):
            flag = " [STALLED]" if stalled.get(actor) else ""
            parts.append("%s %.1fs%s" % (actor, ages[actor], flag))
        lines.append("heartbeat ages: " + "  ".join(parts))
        stalls = metrics.get("ptpu_health_stalls_total", 0)
        if stalls:
            lines.append("stalls detected: %d (see the flight record)"
                         % int(stalls))

    # -- degradations by cause
    degr = _labeled(metrics, "ptpu_degradations_total")
    degr = {k: v for k, v in degr.items() if v}
    if degr:
        lines.append("degradations (ptpu_degradations_total): " + "  ".join(
            "%s=%d" % (c, degr[c]) for c in sorted(degr)))

    # -- cache-tier funnel (ISSUE 8 families — dedicated panel, not "other")
    tier_hits = _labeled(metrics, "ptpu_io_tier_hits_total")
    tier_bytes = _labeled(metrics, "ptpu_io_tier_bytes_total")
    if any(tier_hits.values()):
        lines.append("cache tiers:  " + "  ".join(
            "%s hits=%d (%.1f MB)" % (t, int(tier_hits.get(t, 0)),
                                      tier_bytes.get(t, 0) / 1e6)
            for t in ("mem", "disk", "remote") if tier_hits.get(t)))

    # -- remote read path (ISSUE 8): GETs, hedging, footer cache
    r = {name: metrics[name] for name in metrics
         if name.startswith(("ptpu_io_remote_", "ptpu_io_hedge",
                             "ptpu_io_footer_cache_"))}
    scalar_gets = r.get("ptpu_io_remote_gets_total", 0)
    if scalar_gets:
        lines.append(
            "remote io:    gets=%d (%.1f MB)  hedges=%d (wins=%d)  "
            "sparse_fallbacks=%d"
            % (int(scalar_gets), r.get("ptpu_io_remote_bytes_total", 0) / 1e6,
               int(r.get("ptpu_io_hedges_total", 0)),
               int(r.get("ptpu_io_hedge_wins_total", 0)),
               int(r.get("ptpu_io_remote_sparse_fallbacks_total", 0))))
        fc_hits = r.get("ptpu_io_footer_cache_hits_total", 0)
        fc_miss = r.get("ptpu_io_footer_cache_misses_total", 0)
        if fc_hits or fc_miss:
            lines.append(
                "footer cache: hits=%d misses=%d evictions=%d "
                "invalidations=%d (%.1f MB held)"
                % (int(fc_hits), int(fc_miss),
                   int(r.get("ptpu_io_footer_cache_evictions_total", 0)),
                   int(r.get("ptpu_io_footer_cache_invalidations_total", 0)),
                   r.get("ptpu_io_footer_cache_bytes", 0) / 1e6))
        get_hists = [(n, v) for n, v in sorted(r.items())
                     if n.startswith("ptpu_io_remote_get_seconds")
                     and isinstance(v, dict)]
        for name, h in get_hists:
            label = name[len("ptpu_io_remote_get_seconds"):] or "{}"
            lines.append("  GET %-28s p50 %s  p99 %s ms  ×%d"
                         % (label, _fmt_ms(h.get("p50", 0)),
                            _fmt_ms(h.get("p99", 0)), h.get("count", 0)))

    # -- dataset watch (ISSUE 11): mutation counters, excluded from "other"
    ds = {name[len("ptpu_dataset_"):]: v for name, v in metrics.items()
          if name.startswith("ptpu_dataset_") and isinstance(v, (int, float))}
    if any(ds.values()):
        lines.append(
            "dataset watch: added=%d removed=%d rewritten=%d extensions=%d "
            "generation_conflicts=%d"
            % (int(ds.get("pieces_added_total", 0)),
               int(ds.get("pieces_removed_total", 0)),
               int(ds.get("pieces_rewritten_total", 0)),
               int(ds.get("plan_extensions_total", 0)),
               int(ds.get("generation_conflicts_total", 0))))

    # -- declarative transform ops (ISSUE 9): per-fused-stage timings
    ops = _labeled(metrics, "ptpu_transform_seconds")
    ops = {k: v for k, v in ops.items() if isinstance(v, dict)}
    if ops:
        lines.append("transform ops (ptpu_transform_seconds):  %8s %8s %8s"
                     % ("p50", "p99", "count"))
        for op in sorted(ops, key=lambda o: -ops[o].get("sum", 0)):
            h = ops[op]
            lines.append("  %-28s %s %s %8d"
                         % (op, _fmt_ms(h.get("p50", 0)),
                            _fmt_ms(h.get("p99", 0)), h.get("count", 0)))
        rows_total = metrics.get("ptpu_transform_rows_total")
        if rows_total:
            lines.append("  transform rows total: %d" % int(rows_total))

    # -- provenance / critical-path attribution (ISSUE 10)
    prov_self = {name[len("ptpu_prov_self_s_"):]: v
                 for name, v in metrics.items()
                 if name.startswith("ptpu_prov_self_s_")}
    if prov_self:
        total = sum(prov_self.values()) or 1.0
        top = sorted(prov_self.items(), key=lambda kv: -kv[1])
        lines.append("attribution (critical-path self time, "
                     "%d items / %d batches):"
                     % (int(metrics.get("ptpu_prov_items", 0)),
                        int(metrics.get("ptpu_prov_batches", 0))))
        for site, sec in top[:8]:
            lines.append("  %-28s %9.3fs  %5.1f%%"
                         % (site, sec, 100.0 * sec / total))
        quarantined = metrics.get("ptpu_prov_quarantined", 0)
        if quarantined:
            lines.append("  quarantined items: %d" % int(quarantined))

    # -- everything else, compact (numbers only; histogram summaries as p50s)
    shown_prefixes = ("ptpu_pipeline_", "ptpu_worker_item_seconds",
                      "ptpu_health_", "ptpu_degradations_total",
                      "ptpu_io_tier_", "ptpu_io_remote_", "ptpu_io_hedge",
                      "ptpu_io_footer_cache_", "ptpu_transform_",
                      "ptpu_prov_", "ptpu_dataset_")
    rest = {n: v for n, v in metrics.items()
            if not n.startswith(shown_prefixes)}
    scalars = [(n, v) for n, v in sorted(rest.items())
               if isinstance(v, (int, float))]
    hists = [(n, v) for n, v in sorted(rest.items()) if isinstance(v, dict)]
    if scalars:
        width = max(len(n) for n, _v in scalars)
        lines.append("other metrics:")
        for name, value in scalars:
            if isinstance(value, float) and not float(value).is_integer():
                lines.append("  %-*s %12.4f" % (width, name, value))
            else:
                lines.append("  %-*s %12d" % (width, name, int(value)))
    for name, h in hists:
        lines.append("  %s  count=%d mean=%.2fms p50=%.2fms p90=%.2fms "
                     "p99=%.2fms"
                     % (name, h.get("count", 0), h.get("mean", 0.0) * 1e3,
                        h.get("p50", 0.0) * 1e3, h.get("p90", 0.0) * 1e3,
                        h.get("p99", 0.0) * 1e3))
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-stats",
        description="Live dashboard for a petastorm_tpu metrics snapshot "
                    "(Reporter JSONL stream or Prometheus text file).")
    parser.add_argument(
        "path", nargs="?",
        default=os.environ.get("PTPU_STATS_PATH", "ptpu_stats.jsonl"),
        help="snapshot file (default: $PTPU_STATS_PATH or ./ptpu_stats.jsonl)")
    parser.add_argument("--watch", nargs="?", metavar="SECONDS",
                        const=2.0, default=None,
                        help="redraw every SECONDS (default 2) until "
                             "interrupted")
    parser.add_argument("--once", action="store_true",
                        help="render exactly one frame and exit (with --watch: "
                             "one watch-mode frame, no screen clear — the CI "
                             "render check)")
    args = parser.parse_args(argv)
    if isinstance(args.watch, str):
        # `--watch FILE` (the documented default-interval form): argparse's
        # greedy nargs="?" consumes the path as the SECONDS value — reclaim it
        try:
            args.watch = float(args.watch)
        except ValueError:
            if args.path != parser.get_default("path"):
                parser.error("invalid --watch interval: %r" % args.watch)
            args.path = args.watch
            args.watch = 2.0

    def show():
        try:
            metrics = _load_snapshot(args.path)
        except (OSError, ValueError) as e:
            print("petastorm-tpu-stats: cannot read %s: %s" % (args.path, e),
                  file=sys.stderr)
            return 1
        if not metrics:
            print("petastorm-tpu-stats: no snapshot in %s yet" % args.path,
                  file=sys.stderr)
            return 1
        title = "petastorm-tpu-stats · %s · %s" % (
            args.path, time.strftime("%H:%M:%S"))
        print(render_dashboard(metrics, title=title))
        return 0

    if args.watch is None or args.once:
        return show()
    try:
        while True:
            # ANSI clear+home (no os.system shell-out): redraw in place
            sys.stdout.write("\x1b[2J\x1b[H")
            show()
            time.sleep(max(0.2, args.watch))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
