"""``petastorm-tpu-stats``: live terminal dashboard for a run's metrics.

Reads what a :class:`petastorm_tpu.obs.export.Reporter` writes — a JSONL
snapshot stream (last line wins, so it works against a file another process is
appending to) or a Prometheus text file — and renders one dashboard frame:
stage latency percentiles, queue depths, heartbeat ages (with stalled actors
flagged), per-worker latencies, degradation counts, and the bottleneck
analyzer's verdict (``straggler`` included when per-worker data is present).
In ``--watch`` mode (and against multi-line JSONL streams) the frame gains
sparkline trend columns (rows/s, stage p99, mem-tier hit share) and
window-over-window deltas on the dataset-watch/attribution panels (ISSUE 12).

    petastorm-tpu-stats run_stats.jsonl            # one frame
    petastorm-tpu-stats --watch run_stats.jsonl    # redraw every 2s
    petastorm-tpu-stats --watch 0.5 metrics.prom   # redraw every 0.5s
    petastorm-tpu-stats --watch --once stats.jsonl # render ONE watch frame (CI)
    petastorm-tpu-stats --merge a.jsonl b.json ... # fleet merge (ISSUE 12):
        aggregate several processes'/hosts' exports (Reporter JSONL streams
        and/or the scrape endpoint's /timelines JSON documents) into one
        fleet dashboard — totals summed per family, per-source breakdown,
        fleet rows/s sparkline — windows aligned on each source's
        (wall, perf) clock anchor, not its skew-prone wall stamps.

Exit codes: 0 printed a snapshot, 1 no snapshot found / unreadable file.
"""
from __future__ import annotations

import argparse
import os
import re
import sys
import time


def _load_snapshot(path):
    """{metric full name: number-or-histogram-summary} from either format."""
    from petastorm_tpu.obs.export import (
        parse_prometheus_text,
        read_latest_jsonl_snapshot,
    )

    with open(path, "r") as f:
        head = f.read(1)
    if head == "{":  # Reporter JSONL stream
        obj = read_latest_jsonl_snapshot(path)
        return None if obj is None else obj["metrics"]
    with open(path, "r") as f:
        return _fold_prom_histograms(parse_prometheus_text(f.read()))


def _load_history(path, limit=40):
    """Recent ``(t, metrics)`` snapshots from a Reporter JSONL stream, oldest
    first (the sparkline feed); None for Prometheus files (the watch loop
    accumulates its own frames there). Times sit on the anchored timeline
    when the stream carries the v2 (wall, perf) anchor."""
    from petastorm_tpu.obs.export import read_recent_jsonl_snapshots
    from petastorm_tpu.obs.timeseries import _anchored_t

    with open(path, "r") as f:
        if f.read(1) != "{":
            return None
    return [(_anchored_t(snap), snap["metrics"])
            for snap in read_recent_jsonl_snapshots(path, limit=limit)]


_BUCKET_RE = re.compile(r"^(?P<name>\w+)_bucket(?P<labels>\{.*\})$")


def _fold_prom_histograms(samples):
    """Collapse Prometheus ``_bucket``/``_sum``/``_count`` sample triplets into
    the same summary-dict shape JSONL snapshots carry (count/sum/mean/p50/p90/
    p99), so the renderer has ONE histogram representation."""
    out = {}
    hists = {}  # base full name (labels minus le) -> [(upper, cumulative)]
    for name, value in samples.items():
        m = _BUCKET_RE.match(name)
        if m:
            # anchor `le` as a whole label name: an unanchored match would
            # also hit inside labels merely ENDING in le (handle=, role=)
            labels = re.sub(r',le="[^"]*"|(?<=\{)le="[^"]*",?', "",
                            m.group("labels"))
            labels = "" if labels == "{}" else labels
            base = m.group("name") + labels
            le = re.search(r'(?<=[{,])le="([^"]*)"', name).group(1)
            upper = float("inf") if le == "+Inf" else float(le)
            hists.setdefault(base, []).append((upper, value))
            continue
        out[name] = value
    for base, buckets in hists.items():
        count = out.pop(base + "_count", None)
        total = out.pop(base + "_sum", 0.0)
        # reconstruct labeled _count/_sum keys too (labels ride on base)
        if count is None:
            bare = re.match(r"^(\w+)(\{.*\})?$", base)
            count = out.pop("%s_count%s" % (bare.group(1), bare.group(2) or ""),
                            None)
            total = out.pop("%s_sum%s" % (bare.group(1), bare.group(2) or ""),
                            0.0)
        buckets.sort()
        if count is None:
            count = buckets[-1][1] if buckets else 0
        summary = {"count": int(count), "sum": total,
                   "mean": (total / count) if count else 0.0}
        for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
            target = q * count
            val = 0.0
            prev_finite = 0.0
            if count:
                for upper, cum in buckets:
                    if cum >= target:
                        # +Inf bucket matches: report the last finite bound
                        val = prev_finite if upper == float("inf") else upper
                        break
                    if upper != float("inf"):
                        prev_finite = upper
            summary[key] = val
        out[base] = summary
    return out


def _labeled(metrics, family):
    """``{label value: metric value}`` for one single-label family."""
    out = {}
    prefix = family + "{"
    for name, value in metrics.items():
        if name.startswith(prefix):
            m = re.search(r'="([^"]*)"', name)
            if m:
                out[m.group(1)] = value
    return out


def _pipeline_stats_from(metrics):
    """Reconstruct a ``PipelineStats.snapshot()``-shaped dict from the exported
    ``ptpu_pipeline_*`` families (None when the run exported none)."""
    prefix = "ptpu_pipeline_"
    snap = {}
    for name, value in metrics.items():
        if name.startswith(prefix) and "{" not in name \
                and isinstance(value, (int, float)):
            snap[name[len(prefix):]] = value
    return snap or None


def _fmt_ms(v):
    return "%8.2f" % (v * 1e3)


def _history_series(history, fn):
    """Apply ``fn(metrics) -> value|None`` over snapshot history (oldest
    first); returns the value series."""
    return [fn(m) for _t, m in history]


def _delta_series(history, key):
    """Window deltas of one cumulative scalar across the history, divided by
    the window length (a rate series); None where the series is absent."""
    out = []
    prev = None
    for t, m in history:
        v = m.get(key)
        if not isinstance(v, (int, float)):
            out.append(None)
            prev = None
            continue
        if prev is None:
            out.append(None)
        else:
            pv, pt = prev
            dt = max(1e-9, t - pt)
            out.append(max(0.0, v - pv) / dt)
        prev = (v, t)
    return out


def _render_trends(lines, history):
    """Sparkline trend panel over the snapshot history (ISSUE 12): rows/s
    and mem-tier hit share from window deltas, read p99 from each snapshot's
    cumulative histogram summary (JSONL lines carry summaries, not buckets —
    the label says "cum"; scrape ``/timelines`` for true per-window p99)."""
    from petastorm_tpu.obs.timeseries import sparkline

    if len(history) < 3:
        return
    rows = _delta_series(history, "ptpu_pipeline_rows")

    def stage_p99(m):
        s = m.get('ptpu_pipeline_stage_seconds{stage="read"}')
        return s.get("p99") if isinstance(s, dict) else None

    p99s = _history_series(history, stage_p99)

    def mem_share(m):
        hits = {t: m.get('ptpu_io_tier_hits_total{tier="%s"}' % t, 0)
                for t in ("mem", "arena", "disk", "remote")}
        total = sum(v for v in hits.values() if isinstance(v, (int, float)))
        return (hits.get("mem", 0) / total) if total else None

    shares = _history_series(history, mem_share)

    # arena trends (ISSUE 18 satellite): hit ratio from window deltas of the
    # hit/miss counters (the CUMULATIVE ratio the static panel shows goes
    # flat the moment the warm set stabilizes — the windowed one moves), and
    # resident bytes straight off the gauge
    arena_hit_rates = _delta_series(history, "ptpu_io_arena_hits_total")
    arena_miss_rates = _delta_series(history, "ptpu_io_arena_misses_total")
    arena_ratio = []
    for h, miss in zip(arena_hit_rates, arena_miss_rates):
        if h is None and miss is None:
            arena_ratio.append(None)
            continue
        h, miss = h or 0.0, miss or 0.0
        arena_ratio.append(h / (h + miss) if (h + miss) else None)

    def arena_bytes(m):
        v = m.get("ptpu_io_arena_bytes")
        return v if isinstance(v, (int, float)) and v else None

    arena_res = _history_series(history, arena_bytes)
    panel = []
    for label, series, fmt in (
            ("rows/s", rows, lambda v: "%.0f" % v),
            ("read p99 ms (cum)", p99s, lambda v: "%.2f" % (v * 1e3)),
            ("mem-tier share", shares, lambda v: "%.0f%%" % (100 * v)),
            ("arena hit ratio", arena_ratio, lambda v: "%.0f%%" % (100 * v)),
            ("arena res MB", arena_res, lambda v: "%.1f" % (v / 1e6))):
        present = [v for v in series if v is not None]
        if not present:
            continue
        panel.append("  %-16s %s  now %s"
                     % (label, sparkline(series), fmt(present[-1])))
    if panel:
        lines.append("trends (last %d windows):" % len(history))
        lines.extend(panel)


def _fmt_delta(cur, prev, as_int=True):
    """`` (+N this window)`` suffix, empty when unchanged/unknown."""
    if prev is None or cur is None:
        return ""
    d = cur - prev
    if not d:
        return ""
    return " (%+d this window)" % d if as_int else " (%+.3f this window)" % d


def render_dashboard(metrics, title="", history=None):
    """One dashboard frame (a plain string — the CLI prints it, tests assert
    on it). Sections appear only when their families are present, so the same
    renderer serves a bare-metrics run and a full health-enabled one.
    ``history`` is an optional oldest-first ``[(t, metrics)]`` list (the
    current snapshot last) enabling the sparkline trend panel and the
    window-over-window deltas."""
    lines = []
    history = history or []
    prev_metrics = history[-2][1] if len(history) >= 2 else {}
    if title:
        lines.append(title)
        lines.append("=" * min(78, max(20, len(title))))

    snap = _pipeline_stats_from(metrics)
    worker_lat = _labeled(metrics, "ptpu_worker_item_seconds")
    worker_lat = {k: v for k, v in worker_lat.items() if isinstance(v, dict)}

    # -- verdict first: the one line an operator reads under pager pressure
    # (computed ONCE per frame; the utilization detail rides right below it)
    if snap is not None and snap.get("batches"):
        from petastorm_tpu.obs.analyze import analyze_snapshot

        report = analyze_snapshot(snap, worker_latency=worker_lat or None)
        lines.append("verdict: %s" % report.verdict)
        lines.append("  %s" % report.reason)
        if report.utilization:
            lines.append("  utilization: " + "  ".join(
                "%s %.0f%%" % (side, 100 * report.utilization[side])
                for side in sorted(report.utilization)))

    # -- pipeline counters + queue depths
    if snap is not None:
        lines.append("pipeline: rows=%d batches=%d  host_queue=%d  "
                     "device_queue=%d"
                     % (snap.get("rows", 0), snap.get("batches", 0),
                        snap.get("host_queue_depth", 0),
                        snap.get("device_queue_depth", 0)))

    # -- sparkline trends over the snapshot history (ISSUE 12)
    _render_trends(lines, history)

    # -- stage latency percentiles
    stages = _labeled(metrics, "ptpu_pipeline_stage_seconds")
    stages = {k: v for k, v in stages.items() if isinstance(v, dict)}
    if stages:
        lines.append("stage latencies (ms):   %8s %8s %8s %8s"
                     % ("p50", "p90", "p99", "count"))
        for stage in sorted(stages):
            s = stages[stage]
            lines.append("  %-20s %s %s %s %8d"
                         % (stage, _fmt_ms(s.get("p50", 0)),
                            _fmt_ms(s.get("p90", 0)), _fmt_ms(s.get("p99", 0)),
                            s.get("count", 0)))

    # -- per-worker latency (straggler fodder)
    if worker_lat:
        from petastorm_tpu.obs.analyze import detect_straggler

        straggler = detect_straggler(worker_lat)
        parts = []
        for w in sorted(worker_lat):
            s = worker_lat[w]
            flag = " [STRAGGLER]" if straggler \
                and straggler["worker"] == str(w) else ""
            parts.append("w%s %.1fms×%d%s"
                         % (w, s.get("mean", 0) * 1e3, s.get("count", 0), flag))
        lines.append("workers: " + "  ".join(parts))

    # -- heartbeats (the health layer's export)
    ages = {name[len("ptpu_health_hb_age_s_"):]: v
            for name, v in metrics.items()
            if name.startswith("ptpu_health_hb_age_s_")}
    if ages:
        stalled = {name[len("ptpu_health_hb_stalled_"):]: v
                   for name, v in metrics.items()
                   if name.startswith("ptpu_health_hb_stalled_")}
        parts = []
        for actor in sorted(ages, key=lambda a: -ages[a]):
            flag = " [STALLED]" if stalled.get(actor) else ""
            parts.append("%s %.1fs%s" % (actor, ages[actor], flag))
        lines.append("heartbeat ages: " + "  ".join(parts))
        stalls = metrics.get("ptpu_health_stalls_total", 0)
        if stalls:
            lines.append("stalls detected: %d (see the flight record)"
                         % int(stalls))

    # -- degradations by cause
    degr = _labeled(metrics, "ptpu_degradations_total")
    degr = {k: v for k, v in degr.items() if v}
    if degr:
        lines.append("degradations (ptpu_degradations_total): " + "  ".join(
            "%s=%d" % (c, degr[c]) for c in sorted(degr)))

    # -- cache-tier funnel (ISSUE 8 families — dedicated panel, not "other")
    tier_hits = _labeled(metrics, "ptpu_io_tier_hits_total")
    tier_bytes = _labeled(metrics, "ptpu_io_tier_bytes_total")
    if any(tier_hits.values()):
        lines.append("cache tiers:  " + "  ".join(
            "%s hits=%d (%.1f MB)" % (t, int(tier_hits.get(t, 0)),
                                      tier_bytes.get(t, 0) / 1e6)
            for t in ("mem", "arena", "disk", "remote") if tier_hits.get(t)))

    # -- host-wide cache arena (ISSUE 17 — dedicated panel, not "other")
    arena_entries = metrics.get("ptpu_io_arena_entries", 0)
    arena_admits = metrics.get("ptpu_io_arena_admits_total", 0)
    if arena_entries or arena_admits:
        arena_hits = metrics.get("ptpu_io_arena_hits_total", 0)
        arena_misses = metrics.get("ptpu_io_arena_misses_total", 0)
        looked = arena_hits + arena_misses
        lines.append(
            "cache arena:  mapped=%.1f MB in %d entries  attaches=%d  "
            "hit-rate=%s  admits=%d  evict=%d inval=%d revoked=%d"
            % (metrics.get("ptpu_io_arena_bytes", 0) / 1e6, int(arena_entries),
               int(metrics.get("ptpu_io_arena_attaches_total", 0)),
               ("%.0f%%" % (100.0 * arena_hits / looked)) if looked else "n/a",
               int(arena_admits),
               int(metrics.get("ptpu_io_arena_evictions_total", 0)),
               int(metrics.get("ptpu_io_arena_invalidations_total", 0)),
               int(metrics.get("ptpu_io_arena_holders_revoked_total", 0))))

    # -- per-tenant accounting (ISSUE 18 — who ate the shared resources)
    from petastorm_tpu.obs.tenant import TenantUsageReport

    tenant_report = TenantUsageReport.from_metrics(metrics)
    if tenant_report.tenants():
        lines.extend(tenant_report.render())

    # -- remote read path (ISSUE 8): GETs, hedging, footer cache
    r = {name: metrics[name] for name in metrics
         if name.startswith(("ptpu_io_remote_", "ptpu_io_hedge",
                             "ptpu_io_footer_cache_"))}
    scalar_gets = r.get("ptpu_io_remote_gets_total", 0)
    if scalar_gets:
        lines.append(
            "remote io:    gets=%d (%.1f MB)  hedges=%d (wins=%d)  "
            "sparse_fallbacks=%d"
            % (int(scalar_gets), r.get("ptpu_io_remote_bytes_total", 0) / 1e6,
               int(r.get("ptpu_io_hedges_total", 0)),
               int(r.get("ptpu_io_hedge_wins_total", 0)),
               int(r.get("ptpu_io_remote_sparse_fallbacks_total", 0))))
        fc_hits = r.get("ptpu_io_footer_cache_hits_total", 0)
        fc_miss = r.get("ptpu_io_footer_cache_misses_total", 0)
        if fc_hits or fc_miss:
            lines.append(
                "footer cache: hits=%d misses=%d evictions=%d "
                "invalidations=%d (%.1f MB held)"
                % (int(fc_hits), int(fc_miss),
                   int(r.get("ptpu_io_footer_cache_evictions_total", 0)),
                   int(r.get("ptpu_io_footer_cache_invalidations_total", 0)),
                   r.get("ptpu_io_footer_cache_bytes", 0) / 1e6))
        get_hists = [(n, v) for n, v in sorted(r.items())
                     if n.startswith("ptpu_io_remote_get_seconds")
                     and isinstance(v, dict)]
        for name, h in get_hists:
            label = name[len("ptpu_io_remote_get_seconds"):] or "{}"
            lines.append("  GET %-28s p50 %s  p99 %s ms  ×%d"
                         % (label, _fmt_ms(h.get("p50", 0)),
                            _fmt_ms(h.get("p99", 0)), h.get("count", 0)))

    # -- dataset watch (ISSUE 11): mutation counters, excluded from "other";
    # window-over-window deltas ride along when history is present (ISSUE 12)
    ds = {name[len("ptpu_dataset_"):]: v for name, v in metrics.items()
          if name.startswith("ptpu_dataset_") and isinstance(v, (int, float))}
    if any(ds.values()):
        def _ds_prev(key):
            v = prev_metrics.get("ptpu_dataset_" + key)
            return int(v) if isinstance(v, (int, float)) else None

        parts = []
        for label, key in (("added", "pieces_added_total"),
                           ("removed", "pieces_removed_total"),
                           ("rewritten", "pieces_rewritten_total"),
                           ("extensions", "plan_extensions_total"),
                           ("generation_conflicts",
                            "generation_conflicts_total")):
            cur = int(ds.get(key, 0))
            parts.append("%s=%d%s" % (label, cur,
                                      _fmt_delta(cur, _ds_prev(key))))
        lines.append("dataset watch: " + " ".join(parts))

    # -- declarative transform ops (ISSUE 9): per-fused-stage timings
    ops = _labeled(metrics, "ptpu_transform_seconds")
    ops = {k: v for k, v in ops.items() if isinstance(v, dict)}
    if ops:
        lines.append("transform ops (ptpu_transform_seconds):  %8s %8s %8s"
                     % ("p50", "p99", "count"))
        for op in sorted(ops, key=lambda o: -ops[o].get("sum", 0)):
            h = ops[op]
            lines.append("  %-28s %s %s %8d"
                         % (op, _fmt_ms(h.get("p50", 0)),
                            _fmt_ms(h.get("p99", 0)), h.get("count", 0)))
        rows_total = metrics.get("ptpu_transform_rows_total")
        if rows_total:
            lines.append("  transform rows total: %d" % int(rows_total))

    # -- provenance / critical-path attribution (ISSUE 10); per-site
    # window-over-window self-time deltas when history is present (ISSUE 12)
    prov_self = {name[len("ptpu_prov_self_s_"):]: v
                 for name, v in metrics.items()
                 if name.startswith("ptpu_prov_self_s_")}
    if prov_self:
        total = sum(prov_self.values()) or 1.0
        top = sorted(prov_self.items(), key=lambda kv: -kv[1])
        lines.append("attribution (critical-path self time, "
                     "%d items / %d batches):"
                     % (int(metrics.get("ptpu_prov_items", 0)),
                        int(metrics.get("ptpu_prov_batches", 0))))
        for site, sec in top[:8]:
            prev_sec = prev_metrics.get("ptpu_prov_self_s_" + site)
            if not isinstance(prev_sec, (int, float)):
                prev_sec = None
            lines.append("  %-28s %9.3fs  %5.1f%%%s"
                         % (site, sec, 100.0 * sec / total,
                            _fmt_delta(sec, prev_sec, as_int=False)))
        quarantined = metrics.get("ptpu_prov_quarantined", 0)
        if quarantined:
            lines.append("  quarantined items: %d" % int(quarantined))

    # -- compressed-page pass-through (ISSUE 14): pages/bytes shipped,
    # H2D bytes saved, per-column fallbacks, inflate-stage latency
    pd_pages = metrics.get("ptpu_pagedec_pages_total", 0)
    pd_fallbacks = metrics.get("ptpu_pagedec_fallback_columns_total", 0)
    if pd_pages or pd_fallbacks:
        shipped = metrics.get("ptpu_pagedec_bytes_compressed_total", 0)
        saved = metrics.get("ptpu_pagedec_bytes_saved_h2d_total", 0)
        raw = shipped + saved
        lines.append(
            "pagedec pass-through: pages=%d  shipped=%.1f MB  "
            "saved=%.1f MB%s  fallback columns=%d"
            % (int(pd_pages), shipped / 1e6, saved / 1e6,
               ("  (%.0f%% of raw)" % (100.0 * shipped / raw)) if raw else "",
               int(pd_fallbacks)))
        inflate = metrics.get("ptpu_pagedec_inflate_seconds")
        if isinstance(inflate, dict) and inflate.get("count"):
            lines.append("  inflate stage: p50=%s p99=%s over %d batches"
                         % (_fmt_ms(inflate.get("p50", 0)),
                            _fmt_ms(inflate.get("p99", 0)),
                            int(inflate.get("count", 0))))

    # -- transport plane (ISSUE 15): link traffic, reconnects, heartbeat
    # misses, rtt — excluded from the catch-all; window-over-window deltas
    # on the fault counters when history is present
    net_frames = _labeled(metrics, "ptpu_net_frames_total")
    net_connects = metrics.get("ptpu_net_connects_total", 0)
    if net_connects or any(net_frames.values()):
        net_bytes = _labeled(metrics, "ptpu_net_bytes_total")

        def _net_prev(key):
            v = prev_metrics.get("ptpu_net_%s_total" % key)
            return int(v) if isinstance(v, (int, float)) else None

        reconnects = int(metrics.get("ptpu_net_reconnects_total", 0))
        missed = int(metrics.get("ptpu_net_heartbeats_missed_total", 0))
        corrupt = int(metrics.get("ptpu_net_frames_corrupt_total", 0))
        lines.append(
            "transport:    connects=%d  reconnects=%d%s  hb_missed=%d%s  "
            "corrupt_frames=%d%s"
            % (int(net_connects),
               reconnects, _fmt_delta(reconnects, _net_prev("reconnects")),
               missed, _fmt_delta(missed, _net_prev("heartbeats_missed")),
               corrupt, _fmt_delta(corrupt, _net_prev("frames_corrupt"))))
        lines.append(
            "  frames tx=%d (%.1f MB)  rx=%d (%.1f MB)"
            % (int(net_frames.get("tx", 0)), net_bytes.get("tx", 0) / 1e6,
               int(net_frames.get("rx", 0)), net_bytes.get("rx", 0) / 1e6))
        rtt = metrics.get("ptpu_net_rtt_seconds")
        if isinstance(rtt, dict) and rtt.get("count"):
            lines.append("  rtt: p50=%s p99=%s ms over %d heartbeat echoes"
                         % (_fmt_ms(rtt.get("p50", 0)),
                            _fmt_ms(rtt.get("p99", 0)),
                            int(rtt.get("count", 0))))

    # -- SLO alerts (ISSUE 12): debounced breach/anomaly counters
    slo = _labeled(metrics, "ptpu_slo_alerts_total")
    slo = {k: v for k, v in slo.items() if v}
    if slo:
        lines.append("slo alerts: " + "  ".join(
            "%s=%d" % (name, int(slo[name])) for name in sorted(slo)))

    # -- self-tuning controller (ISSUE 13): live knob values vs defaults,
    # decision totals, freeze state — excluded from the catch-all
    knob_names = sorted(name[len("ptpu_ctl_knob_"):]
                        for name in metrics
                        if name.startswith("ptpu_ctl_knob_")
                        and not name.endswith("_default"))
    if knob_names or metrics.get("ptpu_ctl_windows"):
        frozen = metrics.get("ptpu_ctl_frozen", 0)
        actuations = _labeled(metrics, "ptpu_ctl_actuations_total")
        lines.append(
            "controller: windows=%d  actuations=%d  reverts=%d  freezes=%d%s"
            % (int(metrics.get("ptpu_ctl_windows", 0)),
               int(metrics.get("ptpu_ctl_actuations", 0)),
               int(metrics.get("ptpu_ctl_reverts", 0)),
               int(metrics.get("ptpu_ctl_freezes", 0)),
               "  [FROZEN]" if frozen else ""))
        for knob in knob_names:
            value = metrics.get("ptpu_ctl_knob_" + knob, 0)
            default = metrics.get("ptpu_ctl_knob_%s_default" % knob, 0)
            acted = int(actuations.get(knob, 0)) if actuations else 0
            retuned = value != default
            lines.append("  knob %-22s %12s  (default %s)%s%s"
                         % (knob,
                            ("%.4g" % value) if isinstance(value, float)
                            and not float(value).is_integer()
                            else str(int(value)),
                            ("%.4g" % default) if isinstance(default, float)
                            and not float(default).is_integer()
                            else str(int(default)),
                            "  [RETUNED]" if retuned else "",
                            ("  actuations=%d" % acted) if acted else ""))

    # -- data service fleet (ISSUE 19/20): decode-once fan-out, per-worker
    # straggler fodder, starvation, and the advised-vs-actual fleet size
    svc_workers = metrics.get("ptpu_svc_workers", 0)
    svc_decodes = metrics.get("ptpu_svc_decodes_total", 0)
    if svc_workers or svc_decodes or metrics.get("ptpu_svc_trainers", 0):
        served = int(metrics.get("ptpu_svc_served_items_total", 0))
        advised = metrics.get("ptpu_svc_advised_workers", 0)
        advised_part = ""
        if advised:
            gap = int(advised) - int(svc_workers)
            advised_part = "  advised=%d%s" % (
                int(advised),
                "  [GROW +%d]" % gap if gap > 0
                else ("  [SHRINK %d]" % gap if gap < 0 else ""))
        lines.append(
            "service:      workers=%d%s  trainers=%d  jobs=%d  "
            "leases_out=%d  redispatches=%d"
            % (int(svc_workers), advised_part,
               int(metrics.get("ptpu_svc_trainers", 0)),
               int(metrics.get("ptpu_svc_jobs", 0)),
               int(metrics.get("ptpu_svc_leases_outstanding", 0)),
               int(metrics.get("ptpu_svc_lease_redispatch_total", 0))))
        lines.append(
            "  decodes=%d (redecodes=%d)  served=%d  fan-out=%s  "
            "quarantined=%d  starved=%.1fs"
            % (int(svc_decodes),
               int(metrics.get("ptpu_svc_redecodes_total", 0)), served,
               ("%.2fx" % (served / svc_decodes)) if svc_decodes else "n/a",
               int(metrics.get("ptpu_svc_quarantined_total", 0)),
               metrics.get("ptpu_svc_starved_seconds_total", 0.0)))
        leaked = int(metrics.get("ptpu_svc_lease_leaked_total", 0))
        if leaked:
            lines.append("  LEAKED LEASES: %d (dispatcher bug)" % leaked)
        per_worker = _labeled(metrics, "ptpu_svc_worker_decode_seconds")
        per_worker = {k: v for k, v in per_worker.items()
                      if isinstance(v, dict) and v.get("count")}
        if per_worker:
            slowest = max(v.get("p99", 0) for v in per_worker.values())
            lines.append("  per-worker decode (ms):  %8s %8s %8s"
                         % ("p50", "p99", "count"))
            for w in sorted(per_worker,
                            key=lambda w: -per_worker[w].get("p99", 0)):
                h = per_worker[w]
                flag = " [SLOWEST]" if len(per_worker) > 1 \
                    and h.get("p99", 0) == slowest else ""
                lines.append("    %-24s %s %s %8d%s"
                             % (w, _fmt_ms(h.get("p50", 0)),
                                _fmt_ms(h.get("p99", 0)),
                                h.get("count", 0), flag))

    # -- everything else, compact (numbers only; histogram summaries as p50s)
    shown_prefixes = ("ptpu_pipeline_", "ptpu_worker_item_seconds",
                      "ptpu_health_", "ptpu_degradations_total",
                      "ptpu_io_tier_", "ptpu_io_remote_", "ptpu_io_hedge",
                      "ptpu_io_footer_cache_", "ptpu_transform_",
                      "ptpu_prov_", "ptpu_dataset_", "ptpu_slo_",
                      "ptpu_ctl_", "ptpu_pagedec_", "ptpu_net_",
                      "ptpu_io_arena_", "ptpu_tenant_", "ptpu_svc_")
    rest = {n: v for n, v in metrics.items()
            if not n.startswith(shown_prefixes)}
    scalars = [(n, v) for n, v in sorted(rest.items())
               if isinstance(v, (int, float))]
    hists = [(n, v) for n, v in sorted(rest.items()) if isinstance(v, dict)]
    if scalars:
        width = max(len(n) for n, _v in scalars)
        lines.append("other metrics:")
        for name, value in scalars:
            if isinstance(value, float) and not float(value).is_integer():
                lines.append("  %-*s %12.4f" % (width, name, value))
            else:
                lines.append("  %-*s %12d" % (width, name, int(value)))
    for name, h in hists:
        lines.append("  %s  count=%d mean=%.2fms p50=%.2fms p90=%.2fms "
                     "p99=%.2fms"
                     % (name, h.get("count", 0), h.get("mean", 0.0) * 1e3,
                        h.get("p50", 0.0) * 1e3, h.get("p90", 0.0) * 1e3,
                        h.get("p99", 0.0) * 1e3))
    return "\n".join(lines)


def render_merge(exports):
    """Fleet-merge dashboard (ISSUE 12): per-source breakdown + fleet totals
    (counters summed across the sources' last snapshots — unit-pinned by the
    test suite) + the fleet rows/s sparkline on the anchored timeline."""
    from petastorm_tpu.obs.timeseries import (
        fleet_rate_series,
        merge_exports,
        sparkline,
        uniquify_sources,
    )

    exports = uniquify_sources(exports)
    merged = merge_exports(exports)
    lines = ["fleet merge: %d sources" % len(merged["sources"])]
    for export in exports:
        m = export["metrics"]
        rows = m.get("ptpu_pipeline_rows", 0)
        rates = [p.get("rate") for p in
                 export["series"].get("ptpu_pipeline_rows", ())]
        lines.append("  %-28s rows=%-10d %s"
                     % (export["source"], int(rows or 0), sparkline(rates)))
    fleet = fleet_rate_series(exports, "ptpu_pipeline_rows")
    if fleet:
        lines.append("  %-28s peak %.0f rows/s  %s"
                     % ("fleet rows/s", max(v for _t, v in fleet),
                        sparkline([v for _t, v in fleet])))
    lines.append("")
    lines.append(render_dashboard(merged["totals"],
                                  title="fleet totals (summed)"))
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-stats",
        description="Live dashboard for a petastorm_tpu metrics snapshot "
                    "(Reporter JSONL stream or Prometheus text file).")
    parser.add_argument(
        "path", nargs="?",
        default=os.environ.get("PTPU_STATS_PATH", "ptpu_stats.jsonl"),
        help="snapshot file (default: $PTPU_STATS_PATH or ./ptpu_stats.jsonl)")
    parser.add_argument("--watch", nargs="?", metavar="SECONDS",
                        const=2.0, default=None,
                        help="redraw every SECONDS (default 2) until "
                             "interrupted")
    parser.add_argument("--once", action="store_true",
                        help="render exactly one frame and exit (with --watch: "
                             "one watch-mode frame, no screen clear — the CI "
                             "render check)")
    parser.add_argument("--merge", nargs="+", metavar="EXPORT", default=None,
                        help="fleet mode: aggregate several exports (Reporter "
                             "JSONL streams and/or /timelines JSON documents) "
                             "into one dashboard — totals summed, per-source "
                             "breakdown, clock-anchor-aligned windows")
    args = parser.parse_args(argv)
    if args.merge:
        from petastorm_tpu.obs.timeseries import load_export

        exports = []
        for path in args.merge:
            try:
                exports.append(load_export(path))
            except (OSError, ValueError) as e:
                print("petastorm-tpu-stats: cannot read export %s: %s"
                      % (path, e), file=sys.stderr)
                return 1
        print(render_merge(exports))
        return 0
    if isinstance(args.watch, str):
        # `--watch FILE` (the documented default-interval form): argparse's
        # greedy nargs="?" consumes the path as the SECONDS value — reclaim it
        try:
            args.watch = float(args.watch)
        except ValueError:
            if args.path != parser.get_default("path"):
                parser.error("invalid --watch interval: %r" % args.watch)
            args.path = args.watch
            args.watch = 2.0

    #: prometheus files carry no history — the watch loop accumulates its own
    #: frames so the sparklines still move
    from collections import deque

    frame_history = deque(maxlen=40)

    def show():
        # one parse per frame: a JSONL stream's history already contains the
        # latest snapshot (its last entry) — only Prometheus files / empty
        # streams fall through to the single-snapshot loader
        try:
            history = _load_history(args.path)
        except (OSError, ValueError):
            history = None
        if history:
            metrics = history[-1][1]
        else:
            try:
                metrics = _load_snapshot(args.path)
            except (OSError, ValueError) as e:
                print("petastorm-tpu-stats: cannot read %s: %s"
                      % (args.path, e), file=sys.stderr)
                return 1
            if not metrics:
                print("petastorm-tpu-stats: no snapshot in %s yet"
                      % args.path, file=sys.stderr)
                return 1
            frame_history.append((time.time(), metrics))
            history = list(frame_history)
        title = "petastorm-tpu-stats · %s · %s" % (
            args.path, time.strftime("%H:%M:%S"))
        print(render_dashboard(metrics, title=title, history=history))
        return 0

    if args.watch is None or args.once:
        return show()
    try:
        while True:
            # ANSI clear+home (no os.system shell-out): redraw in place
            sys.stdout.write("\x1b[2J\x1b[H")
            show()
            time.sleep(max(0.2, args.watch))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
