"""``petastorm-tpu-stats``: pretty-print a live run's metrics snapshot.

Reads what a :class:`petastorm_tpu.obs.export.Reporter` writes — a JSONL
snapshot stream (last line wins, so it works against a file another process is
appending to) or a Prometheus text file — groups the families, summarizes the
histograms as p50/p90/p99, and, when the pipeline stage families are present,
prints the bottleneck analyzer's verdict.

    petastorm-tpu-stats run_stats.jsonl
    petastorm-tpu-stats --watch 2 run_stats.jsonl   # redraw every 2s
    petastorm-tpu-stats metrics.prom

Exit codes: 0 printed a snapshot, 1 no snapshot found / unreadable file.
"""
from __future__ import annotations

import argparse
import os
import sys


def _load_snapshot(path):
    """{metric full name: number-or-histogram-summary} from either format."""
    from petastorm_tpu.obs.export import (
        parse_prometheus_text,
        read_latest_jsonl_snapshot,
    )

    with open(path, "r") as f:
        head = f.read(1)
    if head == "{":  # Reporter JSONL stream
        obj = read_latest_jsonl_snapshot(path)
        return None if obj is None else obj["metrics"]
    with open(path, "r") as f:
        return parse_prometheus_text(f.read())


def _pipeline_stats_from(metrics):
    """Reconstruct a ``PipelineStats.snapshot()``-shaped dict from the exported
    ``ptpu_pipeline_*`` families (None when the run exported none)."""
    prefix = "ptpu_pipeline_"
    snap = {}
    for name, value in metrics.items():
        if name.startswith(prefix) and "{" not in name \
                and isinstance(value, (int, float)):
            snap[name[len(prefix):]] = value
    return snap or None


def _render(metrics):
    lines = []
    scalars = []
    hists = []
    for name in sorted(metrics):
        value = metrics[name]
        if isinstance(value, dict):  # histogram summary from a JSONL snapshot
            hists.append((name, value))
        else:
            scalars.append((name, value))
    width = max((len(n) for n, _v in scalars), default=0)
    for name, value in scalars:
        if isinstance(value, float) and not value.is_integer():
            lines.append("%-*s %12.4f" % (width, name, value))
        else:
            lines.append("%-*s %12d" % (width, name, int(value)))
    for name, h in hists:
        lines.append("%s  count=%d  mean=%.2fms  p50=%.2fms  p90=%.2fms  "
                     "p99=%.2fms"
                     % (name, h.get("count", 0), h.get("mean", 0.0) * 1e3,
                        h.get("p50", 0.0) * 1e3, h.get("p90", 0.0) * 1e3,
                        h.get("p99", 0.0) * 1e3))
    snap = _pipeline_stats_from(metrics)
    if snap is not None and snap.get("batches"):
        from petastorm_tpu.obs.analyze import analyze_snapshot

        lines.append("")
        lines.append(analyze_snapshot(snap).render())
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-stats",
        description="Pretty-print a petastorm_tpu metrics snapshot "
                    "(Reporter JSONL stream or Prometheus text file).")
    parser.add_argument(
        "path", nargs="?",
        default=os.environ.get("PTPU_STATS_PATH", "ptpu_stats.jsonl"),
        help="snapshot file (default: $PTPU_STATS_PATH or ./ptpu_stats.jsonl)")
    parser.add_argument("--watch", type=float, metavar="SECONDS", default=None,
                        help="redraw every SECONDS until interrupted")
    args = parser.parse_args(argv)

    def show():
        try:
            metrics = _load_snapshot(args.path)
        except (OSError, ValueError) as e:
            print("petastorm-tpu-stats: cannot read %s: %s" % (args.path, e),
                  file=sys.stderr)
            return 1
        if not metrics:
            print("petastorm-tpu-stats: no snapshot in %s yet" % args.path,
                  file=sys.stderr)
            return 1
        print(_render(metrics))
        return 0

    if args.watch is None:
        return show()
    import time

    try:
        while True:
            os.system("clear" if os.name == "posix" else "cls")
            show()
            time.sleep(max(0.2, args.watch))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
