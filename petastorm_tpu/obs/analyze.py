"""Bottleneck analyzer: name the pipeline stage that limits throughput.

The loader pipeline is three actors around two bounded queues::

    producer (reader.next + batch.form) --host queue--> transfer/consumer side
    (decode.dispatch + h2d + training step) ... with the process pool's shm
    wire feeding the producer from below.

``PipelineStats`` already records, per actor, both its WORK time and its WAIT
time on the queue between them — and in a steady-state bounded pipeline those
waits identify the limiting stage exactly: the actor that never waits is the
bottleneck, and everyone upstream piles up on full queues while everyone
downstream starves on empty ones.

Verdicts (the ISSUE-3 taxonomy):

- ``producer-bound`` — the reader side can't keep up: the producer is ~always
  working (never blocked putting into the host queue) while the consumer side
  starves on ``queue_wait_s``. Fix: more workers, a faster wire, less host
  decode.
- ``consumer-bound`` — everything downstream of the host queue limits: decode
  dispatch, H2D, or the training step itself. The producer spends its time
  blocked on a full host queue (``put_wait_s``). Fix: on-device decode, bigger
  prefetch, a faster step.
- ``wire-bound`` — a producer-bound pipeline whose reader time is actually slab
  starvation on the shm wire (``shm_acquire_wait_s`` rivals ``read_s``, or most
  items fell back to the socket): the ring, not the readers, is the limiter.
  Fix: more/bigger slabs, release batches sooner.
- ``straggler`` — a producer-bound pipeline whose reader time is actually ONE
  slow worker: the per-worker latency histograms (recorded when a health
  monitor is attached, ISSUE 5) show one worker's mean item latency far above
  its peers' — a bad disk, a hot row-group shard, a throttled child. Fix: look
  at that worker's host/shard, enable work stealing, or drop the worker.
- ``balanced`` — no stage dominates (utilizations within tolerance), and
  ``idle`` — not enough data to judge.

Utilization per side = work / (work + wait); the verdict is the side with the
higher utilization, refined to wire-bound by the shm gauges. Percentile detail
(p50/p90/p99 per stage) rides along when the loader was built with
``metrics=`` (log-bucketed histograms, :mod:`petastorm_tpu.obs.metrics`).
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class BottleneckReport:
    """Analyzer output: machine-readable verdict + human-readable rendering."""

    verdict: str  # producer-bound | consumer-bound | wire-bound | straggler | balanced | idle
    utilization: dict  # side -> work/(work+wait) fraction
    detail: dict       # the inputs the verdict was computed from
    reason: str
    percentiles: dict | None = None  # stage -> {p50, p90, p99}, when metrics on
    straggler: dict | None = None    # {worker, mean_s, peer_median_s, ratio}, when detected
    transform_ops: dict | None = None  # fused-op label -> histogram summary (ISSUE 9)
    slo_alerts: list | None = None   # recent debounced SLO/anomaly alerts (ISSUE 12)

    def to_dict(self):
        return dataclasses.asdict(self)

    def render(self):
        """Multi-line human-readable report (the ``petastorm-tpu-bench
        --report`` / ``petastorm-tpu-stats`` output)."""
        lines = ["bottleneck: %s" % self.verdict,
                 "  %s" % self.reason]
        for side in sorted(self.utilization):
            lines.append("  %-9s utilization %5.1f%%"
                         % (side, 100.0 * self.utilization[side]))
        d = self.detail
        lines.append("  producer: read %.3fs + batch %.3fs, blocked-on-full-queue %.3fs"
                     % (d["read_s"], d["batch_s"], d["put_wait_s"]))
        lines.append("  consumer: decode %.3fs + h2d %.3fs, starved-on-empty-queue %.3fs"
                     % (d["decode_s"], d["h2d_s"], d["queue_wait_s"]))
        if d.get("shm_acquire_wait_s") or d.get("shm_fallbacks"):
            lines.append("  wire:     slab wait %.3fs, socket fallbacks %d"
                         % (d.get("shm_acquire_wait_s", 0.0),
                            d.get("shm_fallbacks", 0)))
        if d.get("device_queue_wait_s") is not None:
            lines.append("  training loop starved %.3fs on the device queue"
                         % d["device_queue_wait_s"])
        if self.straggler:
            s = self.straggler
            lines.append("  straggler: worker %s mean %.1fms vs peer median "
                         "%.1fms (%.1fx)"
                         % (s["worker"], s["mean_s"] * 1e3,
                            s["peer_median_s"] * 1e3, s["ratio"]))
        if self.percentiles:
            for stage in sorted(self.percentiles):
                p = self.percentiles[stage]
                lines.append("  %-16s p50 %8.2fms  p90 %8.2fms  p99 %8.2fms"
                             % (stage, p["p50"] * 1e3, p["p90"] * 1e3,
                                p["p99"] * 1e3))
        if self.transform_ops:
            lines.append("  transform stage (declarative ops, this process):")
            for op in sorted(self.transform_ops,
                             key=lambda o: -self.transform_ops[o]["sum"]):
                s = self.transform_ops[op]
                lines.append(
                    "    %-20s total %8.3fs over %6d calls  p50 %7.2fms  "
                    "p99 %7.2fms"
                    % (op, s["sum"], s["count"], s["p50"] * 1e3,
                       s["p99"] * 1e3))
        if self.slo_alerts:
            lines.append("  slo alerts (newest last):")
            for alert in self.slo_alerts[-5:]:
                lines.append("    [%s] %s"
                             % (alert.get("cause", "?"),
                                alert.get("message", "")))
        return "\n".join(lines)

    def __str__(self):
        return self.render()


#: a side must beat the other by this much utilization to be called the
#: bottleneck (below it the pipeline is genuinely balanced)
_MARGIN = 0.15
#: slab-wait share of reader time above which producer-bound refines to
#: wire-bound (the readers are mostly waiting for slabs, not reading)
_WIRE_SHARE = 0.5
#: a worker whose mean item latency exceeds its peers' median by this factor
#: (with enough samples on both sides) is a straggler
_STRAGGLER_RATIO = 3.0
#: minimum per-worker item count before its mean is trusted at all
_STRAGGLER_MIN_ITEMS = 4


def detect_straggler(worker_latency, ratio=_STRAGGLER_RATIO,
                     min_items=_STRAGGLER_MIN_ITEMS):
    """One slow worker among peers, or ``None``.

    ``worker_latency`` is ``{worker key: histogram summary}`` (the
    ``HealthMonitor.worker_latency()`` shape — needs ``count`` and ``mean``).
    A straggler verdict needs at least two workers with ``min_items`` each:
    the slowest worker's mean must exceed the MEDIAN of the others' means by
    ``ratio`` (median, not mean, so one straggler cannot drag the baseline up
    with it)."""
    eligible = {k: s for k, s in (worker_latency or {}).items()
                if s.get("count", 0) >= min_items and s.get("mean", 0) > 0}
    if len(eligible) < 2:
        return None
    slowest = max(eligible, key=lambda k: eligible[k]["mean"])
    peers = sorted(eligible[k]["mean"] for k in eligible if k != slowest)
    peer_median = peers[len(peers) // 2]
    if peer_median <= 0 or eligible[slowest]["mean"] < ratio * peer_median:
        return None
    return {"worker": str(slowest),
            "mean_s": round(eligible[slowest]["mean"], 6),
            "peer_median_s": round(peer_median, 6),
            "ratio": round(eligible[slowest]["mean"] / peer_median, 2),
            "items": eligible[slowest].get("count", 0)}


def analyze_snapshot(snap, percentiles=None, worker_latency=None):
    """Analyze one ``PipelineStats.snapshot()``-shaped dict (shm gauges
    optional) into a :class:`BottleneckReport`. ``worker_latency`` (the
    per-worker histogram summaries a health monitor records) refines a
    producer-bound verdict to ``straggler`` when one worker limits the pack."""
    read_s = snap.get("read_s", 0.0)
    batch_s = snap.get("batch_s", 0.0)
    put_wait_s = snap.get("put_wait_s", 0.0)
    decode_s = snap.get("decode_s", 0.0)
    h2d_s = snap.get("h2d_s", 0.0)
    queue_wait_s = snap.get("queue_wait_s", 0.0)
    wire_wait_s = snap.get("shm_acquire_wait_s", 0.0)

    detail = {
        "read_s": round(read_s, 4), "batch_s": round(batch_s, 4),
        "put_wait_s": round(put_wait_s, 4), "decode_s": round(decode_s, 4),
        "h2d_s": round(h2d_s, 4), "queue_wait_s": round(queue_wait_s, 4),
        "device_queue_wait_s": round(snap.get("device_queue_wait_s", 0.0), 4),
        "shm_acquire_wait_s": round(wire_wait_s, 4),
        "shm_fallbacks": snap.get("shm_fallbacks", 0),
        "batches": snap.get("batches", 0),
    }

    producer_work = read_s + batch_s
    producer_total = producer_work + put_wait_s
    consumer_work = decode_s + h2d_s
    consumer_total = consumer_work + queue_wait_s
    # below ~20ms of total measured stage time the fractions are scheduler
    # noise, not a pipeline shape — refuse to name a bottleneck
    if snap.get("batches", 0) == 0 or (producer_total + consumer_total) < 0.02:
        return BottleneckReport(
            verdict="idle", utilization={},
            detail=detail, reason="not enough measured stage time to judge",
            percentiles=percentiles)

    producer_util = producer_work / producer_total if producer_total else 0.0
    consumer_util = consumer_work / consumer_total if consumer_total else 0.0
    utilization = {"producer": round(producer_util, 4),
                   "consumer": round(consumer_util, 4)}

    if producer_util >= consumer_util + _MARGIN:
        # the producer side limits; is it the readers or the shm wire that
        # reader time is actually spent in?
        if read_s > 0 and wire_wait_s >= _WIRE_SHARE * read_s:
            return BottleneckReport(
                "wire-bound", utilization, detail,
                "reader time is dominated by waiting for free shm slabs "
                "(%.3fs slab wait vs %.3fs read) — grow the ring or release "
                "batches sooner" % (wire_wait_s, read_s), percentiles)
        straggler = detect_straggler(worker_latency)
        if straggler is not None:
            return BottleneckReport(
                "straggler", utilization, detail,
                "the reader side is limited by ONE slow worker: worker %s "
                "averages %.1fms per item vs a %.1fms peer median (%.1fx) — "
                "check its host/shard, or rely on work stealing"
                % (straggler["worker"], straggler["mean_s"] * 1e3,
                   straggler["peer_median_s"] * 1e3, straggler["ratio"]),
                percentiles, straggler=straggler)
        return BottleneckReport(
            "producer-bound", utilization, detail,
            "the reader side is saturated (%.0f%% busy) while the consumer "
            "side starves %.3fs on an empty host queue"
            % (100 * producer_util, queue_wait_s), percentiles)
    if consumer_util >= producer_util + _MARGIN:
        return BottleneckReport(
            "consumer-bound", utilization, detail,
            "the decode/transfer/step side is saturated (%.0f%% busy) while "
            "the producer blocks %.3fs on a full host queue"
            % (100 * consumer_util, put_wait_s), percentiles)
    return BottleneckReport(
        "balanced", utilization, detail,
        "no stage dominates (producer %.0f%% vs consumer %.0f%% busy)"
        % (100 * producer_util, 100 * consumer_util), percentiles)


def analyze_loader(loader):
    """:func:`analyze_snapshot` over a live ``DataLoader`` — the implementation
    behind ``DataLoader.bottleneck_report()`` (stage percentiles attached when
    the loader was built with ``metrics=``, per-worker straggler detection when
    it was built with ``health=``)."""
    snap = loader.stats.snapshot()
    percentiles = None
    obs = getattr(loader, "_obs", None)
    if obs is not None:
        percentiles = {}
        for stage, hist in obs.stage_histograms().items():
            s = hist.snapshot()
            percentiles[stage] = {"p50": s["p50"], "p90": s["p90"],
                                  "p99": s["p99"]}
    # the SCOPE (not the monitor): on a shared monitor the straggler detector
    # must compare peers within THIS pipeline's executor only
    scope = getattr(loader, "_health_scope", None)
    worker_latency = scope.worker_latency() if scope is not None else None
    report = analyze_snapshot(snap, percentiles=percentiles,
                              worker_latency=worker_latency)
    # declarative-transform visibility (ISSUE 9): per-fused-op timings from
    # the process-wide registry — live for thread/dummy pools, where the
    # transform runs in this process (pool children keep their own registries)
    from petastorm_tpu.ops.tabular import transform_op_stats

    ops = transform_op_stats()
    if ops:
        report.transform_ops = ops
    # temporal plane (ISSUE 12): recent debounced SLO/anomaly alerts ride on
    # the verdict, so one report shows both the steady-state shape AND any
    # burn the window crossed
    engine = getattr(loader, "_slo_engine", None)
    if engine is not None:
        alerts = engine.alerts()
        if alerts:
            report.slo_alerts = [
                {"name": a.name, "cause": a.cause, "t": a.t,
                 "value": a.value, "culprit": a.culprit,
                 "message": a.message}
                for a in alerts]
    return report
