"""Attribution regression forensics: ``petastorm-tpu-bench diff run_a run_b``
(ISSUE 12).

The trend gate (:mod:`petastorm_tpu.benchmark.trend`) can say *that* rows/s
regressed; this module says *why*: trend entries now carry the per-site
critical-path self-times of their measured workload (the attribution plane's
``stage_self_s``), so two runs can be diffed site by site — "rows/s −28%:
io.remote self-time 2.3×" names the regressed seam instead of leaving the
operator to bisect.

``run_a``/``run_b`` select runs three ways:

- a path to a JSON/JSONL file (the LAST trend-schema entry in it wins — a
  ``BENCH_HISTORY.jsonl`` copy works as-is);
- an integer index into ``--history`` (Python semantics: ``-1`` is the newest
  entry, ``-2`` the one before);
- the words ``latest`` / ``prev`` (aliases for ``-1`` / ``-2``).

The last stdout line is a one-line JSON verdict (``schema
ptpu-bench-diff-v1``) so CI can gate on it; ``--fail-threshold`` makes the
command itself exit 1 on a rows/s regression beyond the fraction.
"""
from __future__ import annotations

import argparse
import json
import os

DIFF_SCHEMA = "ptpu-bench-diff-v1"

#: a site must own at least this share of either run's total self time to be
#: named (sub-noise sites produce huge meaningless ratios)
_MIN_SITE_SHARE = 0.05
#: and its self-time ratio must move at least this much to be called regressed
_MIN_RATIO = 1.25


def _trend_entries(path):
    from petastorm_tpu.benchmark.trend import ACCEPTED_SCHEMAS

    entries = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict) \
                    and obj.get("schema") in ACCEPTED_SCHEMAS:
                entries.append(obj)
    if not entries:
        # a bare JSON file holding one entry (or a list) also works
        with open(path) as f:
            try:
                obj = json.load(f)
            except ValueError:
                obj = None
        if isinstance(obj, dict):
            entries = [obj]
        elif isinstance(obj, list):
            entries = [e for e in obj if isinstance(e, dict)]
    return entries


def load_run(ref, history="BENCH_HISTORY.jsonl"):
    """Resolve one run reference (path / index / latest / prev) to a trend
    entry dict."""
    if isinstance(ref, dict):
        return ref
    ref = str(ref)
    if ref == "latest":
        ref = "-1"
    elif ref == "prev":
        ref = "-2"
    if os.path.exists(ref):
        entries = _trend_entries(ref)
        if not entries:
            raise ValueError("no trend entries in %s" % ref)
        return entries[-1]
    try:
        index = int(ref)
    except ValueError:
        raise ValueError(
            "run reference %r is neither an existing file nor an index into "
            "%s" % (ref, history))
    entries = _trend_entries(history)
    if not entries:
        raise ValueError("no trend entries in history %s" % history)
    try:
        return entries[index]
    except IndexError:
        raise ValueError("history %s has %d entries; index %d out of range"
                         % (history, len(entries), index))


def diff_runs(run_a, run_b):
    """Diff two trend entries (a = baseline, b = candidate) into a forensic
    verdict dict: rows/s movement, per-site self-time ratios over the
    significant sites, the named regressed site (largest significant
    self-time growth), and the one-line human verdict."""
    from petastorm_tpu.obs.critical_path import diff_self_times

    rows_a = run_a.get("rows_per_s") or 0.0
    rows_b = run_b.get("rows_per_s") or 0.0
    rows_delta = (rows_b / rows_a - 1.0) if rows_a else 0.0

    sites_a = run_a.get("sites") or {}
    sites_b = run_b.get("sites") or {}
    site_diffs = diff_self_times(sites_a, sites_b,
                                 min_share=_MIN_SITE_SHARE)
    ratios = {site: round(ratio, 3)
              for site, ratio, _a, _b in site_diffs}
    regressed_site = None
    regressed_ratio = None
    # site_diffs is sorted worst-growth-first: the candidate is its head,
    # named only when the growth clears the ratio bar
    if site_diffs and site_diffs[0][1] >= _MIN_RATIO:
        regressed_site = site_diffs[0][0]
        regressed_ratio = round(site_diffs[0][1], 3)

    parts = ["rows/s %+.1f%%" % (100.0 * rows_delta)]
    if regressed_site is not None:
        parts.append("%s self-time %.1fx (%.3fs -> %.3fs)"
                     % (regressed_site, regressed_ratio,
                        sites_a.get(regressed_site, 0.0),
                        sites_b.get(regressed_site, 0.0)))
    hedge_note = _hedge_note(run_a, run_b)
    if hedge_note:
        parts.append(hedge_note)
    tenant_breakdown, tenant_note = _tenant_breakdown(run_a, run_b)
    if tenant_note:
        parts.append(tenant_note)
    p99_a, p99_b = run_a.get("step_p99_s"), run_b.get("step_p99_s")
    if p99_a and p99_b and p99_a > 0 and p99_b / p99_a >= _MIN_RATIO:
        parts.append("step p99 %.1fx (%.1fms -> %.1fms)"
                     % (p99_b / p99_a, p99_a * 1e3, p99_b * 1e3))
    if regressed_site is None and len(parts) == 1:
        parts.append("no site's critical-path self time moved >=%.2fx at "
                     ">=%d%% share" % (_MIN_RATIO, 100 * _MIN_SITE_SHARE))
    return {
        "schema": DIFF_SCHEMA,
        "rows_per_s_a": round(rows_a, 1),
        "rows_per_s_b": round(rows_b, 1),
        "rows_per_s_delta": round(rows_delta, 4),
        "site_ratios": ratios,
        "regressed_site": regressed_site,
        "regressed_site_ratio": regressed_ratio,
        "workload_a": run_a.get("workload"),
        "workload_b": run_b.get("workload"),
        "tenant_breakdown": tenant_breakdown,
        "verdict": ": ".join([parts[0], ", ".join(parts[1:])]) if parts[1:]
        else parts[0],
    }


def _tenant_breakdown(run_a, run_b):
    """Per-tenant forensics (ISSUE 18 satellite): when BOTH runs carry the
    tenant-dimensioned site map (``"tenants": {tenant: {site: self_s}}`` —
    written by workloads that ran with ``tenant=``-labeled series), diff each
    tenant's critical-path self-times independently and name the worst
    offender: "tenant b's io.remote self-time 2.1x". Returns
    ``(breakdown_dict_or_None, note_or_None)``."""
    from petastorm_tpu.obs.critical_path import diff_self_times

    tenants_a = run_a.get("tenants")
    tenants_b = run_b.get("tenants")
    if not isinstance(tenants_a, dict) or not isinstance(tenants_b, dict):
        return None, None
    breakdown = {}
    worst = None  # (ratio, tenant, site)
    for tenant in sorted(set(tenants_a) & set(tenants_b)):
        diffs = diff_self_times(tenants_a[tenant] or {},
                                tenants_b[tenant] or {},
                                min_share=_MIN_SITE_SHARE)
        breakdown[tenant] = {site: round(ratio, 3)
                             for site, ratio, _a, _b in diffs}
        if diffs and diffs[0][1] >= _MIN_RATIO \
                and (worst is None or diffs[0][1] > worst[0]):
            worst = (diffs[0][1], tenant, diffs[0][0])
    if not breakdown:
        return None, None
    note = None
    if worst is not None:
        note = "tenant %s's %s self-time %.1fx" % (worst[1], worst[2],
                                                   worst[0])
    return breakdown, note


def _hedge_note(run_a, run_b):
    """"hedge win rate halved" style note when both entries carry the remote
    io counters (optional trend fields)."""
    def win_rate(run):
        io = run.get("io") or {}
        hedges = io.get("hedges")
        if not hedges:
            return None
        return io.get("hedge_wins", 0) / hedges

    wa, wb = win_rate(run_a), win_rate(run_b)
    if wa is None or wb is None or wa <= 0:
        return None
    if wb / wa <= 0.6:
        return "hedge win rate %.0f%% -> %.0f%%" % (100 * wa, 100 * wb)
    return None


def render(verdict, run_a, run_b):
    lines = ["bench diff (%s -> %s):"
             % (run_a.get("workload", "?"), run_b.get("workload", "?")),
             "  rows/s %.0f -> %.0f (%+.1f%%)"
             % (verdict["rows_per_s_a"], verdict["rows_per_s_b"],
                100 * verdict["rows_per_s_delta"])]
    if verdict["workload_a"] != verdict["workload_b"]:
        lines.append("  WARNING: different workload fingerprints — rows/s "
                     "numbers are not directly comparable")
    sites_a = run_a.get("sites") or {}
    sites_b = run_b.get("sites") or {}
    for site in sorted(set(sites_a) | set(sites_b),
                       key=lambda s: -(verdict["site_ratios"].get(s, 0))):
        a, b = sites_a.get(site, 0.0), sites_b.get(site, 0.0)
        ratio = verdict["site_ratios"].get(site)
        flag = "  <-- regressed" if site == verdict["regressed_site"] else ""
        lines.append("  %-24s %8.3fs -> %8.3fs self%s%s"
                     % (site, a, b,
                        "  (%.2fx)" % ratio if ratio is not None else "",
                        flag))
    breakdown = verdict.get("tenant_breakdown")
    if breakdown:
        lines.append("  per-tenant self-time ratios:")
        for tenant in sorted(breakdown):
            ratios = breakdown[tenant]
            worst = max(ratios.items(), key=lambda kv: kv[1]) \
                if ratios else None
            lines.append("    %-16s %s" % (tenant, "  ".join(
                "%s %.2fx" % (site, ratios[site])
                for site in sorted(ratios, key=lambda s: -ratios[s])[:4])
                if worst else "(no significant sites)"))
    lines.append("  verdict: %s" % verdict["verdict"])
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-bench diff", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("run_a", help="baseline run: file path, history "
                                      "index, 'latest' or 'prev'")
    parser.add_argument("run_b", help="candidate run (same forms)")
    parser.add_argument("--history", default="BENCH_HISTORY.jsonl",
                        help="history JSONL indices resolve against")
    parser.add_argument("--fail-threshold", type=float, default=None,
                        metavar="FRACTION",
                        help="exit 1 when rows/s regressed more than this "
                             "fraction (default: report only)")
    args = parser.parse_args(argv)

    try:
        run_a = load_run(args.run_a, history=args.history)
        run_b = load_run(args.run_b, history=args.history)
    except ValueError as e:
        print("petastorm-tpu-bench diff: %s" % e)
        return 2
    verdict = diff_runs(run_a, run_b)
    print(render(verdict, run_a, run_b))
    print(json.dumps(verdict))
    if args.fail_threshold is not None \
            and verdict["rows_per_s_delta"] < -args.fail_threshold:
        return 1
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
