"""Structured degradation log: one logger, one counter family, warn-once policy.

Before ISSUE 3 every graceful-degradation path announced itself its own way —
``logger.warning`` in :mod:`petastorm_tpu.workers` for the shm ring falling back
to the socket wire, a module-cache warn-once in ``shm_ring.shm_supported``, a
silent copy-out in ``serializers.py`` — which meant an operator could neither
grep one logger name nor count how often a cause fired. Here every degradation
goes through :func:`degradation`:

- logged on the ``petastorm_tpu.obs`` logger with a machine-greppable
  ``[degradation cause=<cause>]`` suffix, once per cause by default (repeat
  occurrences stay countable without scrolling the log);
- counted on the process-wide registry as
  ``ptpu_degradations_total{cause="<cause>"}`` on EVERY call, so the Prometheus
  export and ``petastorm-tpu-stats`` show the rate even after the log went
  quiet.

Known causes (the stable label values; see docs/observability.md):
``shm_unsupported``, ``shm_ring_create_failed``, ``shm_view_copyout``,
``worker_died``, ``respawn_failed``, ``thread_join_timeout``,
``unsharded_decode`` — from the async read path (ISSUE 4),
``readahead_unavailable``, ``readahead_fallback``, ``memcache_oversized``,
``disk_cache`` — and, from the health layer (ISSUE 5), ``stall_detected`` (a
pipeline actor missed its heartbeat threshold) and ``arrow_fallback`` (an
Arrow-expressible batch failed IPC encode and rode the pickle wire instead)
— and, from the remote read tier (ISSUE 8), ``remote_unavailable`` (the
ranged-GET engine failed to build; classic reads) and ``footer_unreadable``
(a quarantined item's skipped row count is unknown) — and, from the
dataset-watch plane (ISSUE 11), ``dataset_mutated`` (the watcher observed a
removal/rewrite under a running reader), ``piece_removed`` /
``piece_rewritten`` (a plan item quarantined because its file vanished /
changed generation mid-run), and ``watch_error`` (a watch tick failed —
scan, mutate hook, or delta application) — and, from the temporal plane
(ISSUE 12), ``slo_breach`` / ``anomaly_detected`` (a debounced SLO/anomaly
alert fired; the full alert rides into live flight recorders),
``slo_attribution_error``, ``timeline_listener_error`` and
``timeline_sample_error`` (best-effort temporal-plane failures that must
stay visible without killing the cadence) — and, from the transport plane
(ISSUE 15), ``transport_link_down`` (a framed tcp link died — socket error,
EOF, half-open heartbeat trip; warn-once per connection; also the
tcp-unavailable fallback to the pipe pool), ``transport_frame_corrupt`` (a
crc32-trailer/magic rejection, link torn down), ``transport_reconnected``
(the child redialed and the hub re-adopted; un-acked items re-dispatched),
and ``transport_shm_bypass`` (slab wire disabled over tcp — payloads ride
the framed socket frames) — and, from the host-wide cache arena (ISSUE 17),
``arena_unavailable`` (shm/flock unusable, creation or attach failed, or
``PTPU_ARENA=off`` — per-process caches in effect, byte-identical output),
``arena_full`` (an admission declined: payload over budget, budget full of
held entries, or the index outgrew the control segment) and
``arena_lease_revoked`` (a dead process's holder refcounts were reclaimed;
its pinned entries are evictable again).
"""
from __future__ import annotations

import logging
import threading

from petastorm_tpu.obs import flight as _flight
from petastorm_tpu.obs.metrics import default_registry

logger = logging.getLogger("petastorm_tpu.obs")

_lock = threading.Lock()
_announced = set()
_counters = {}  # cause -> Counter, resolved once (hot sites pay one inc())


def _counter(cause):
    counter = _counters.get(cause)
    if counter is None:
        # get-or-create is idempotent, so a racing double-resolve is harmless
        counter = default_registry().counter(
            "ptpu_degradations_total",
            help="graceful-degradation events by cause", cause=cause)
        with _lock:
            _counters[cause] = counter
    return counter


def degradation(cause, message, *args, once=True, level=logging.WARNING):
    """Count + log one degradation occurrence.

    ``cause`` is a short stable slug (the metric label). ``message``/``args``
    are lazy %-formatted like stdlib logging. ``once=True`` (default) logs the
    first occurrence per cause per process and only counts the rest;
    ``once=False`` logs every time (worker deaths, where each event matters).
    Repeat calls for a known cause cost one ``Counter.inc()`` — per-item
    degradation paths (shm view copy-out) stay cheap.

    When a health monitor is live (ISSUE 5), every occurrence is also mirrored
    into its flight-recorder ring so the record written at a stall/crash shows
    which degradations led up to it (one deque append; no monitor = one empty
    list from :func:`petastorm_tpu.obs.flight.active_recorders`).
    """
    _counter(cause).inc()
    for recorder in _flight.active_recorders():
        recorder.record("degradation", cause=cause)
    if once:
        with _lock:
            if cause in _announced:
                return
            _announced.add(cause)
    logger.log(level, message + " [degradation cause=%s]", *(args + (cause,)))


def degradation_counts():
    """``{cause: count}`` so far this process (CLI / test hook)."""
    snap = default_registry().snapshot()
    out = {}
    prefix = "ptpu_degradations_total{cause="
    for name, value in snap.items():
        if name.startswith(prefix):
            out[name[len(prefix):].strip('"}')] = value
    return out


def _reset_announced_for_tests():
    with _lock:
        _announced.clear()
