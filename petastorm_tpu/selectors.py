"""Row-group selectors: prune row groups via prebuilt value→row-group indexes.

Capability parity with petastorm/selectors.py (``RowGroupSelectorBase``, ``SingleIndexSelector``
~L30, ``IntersectIndexSelector``, ``UnionIndexSelector``). Selectors resolve against indexes
built by petastorm_tpu/etl/rowgroup_indexing.py before any scheduling happens.
"""
from __future__ import annotations


class RowGroupSelectorBase:
    def get_index_names(self):
        """Names of the indexes this selector needs."""
        raise NotImplementedError

    def select_row_groups(self, index_dict):
        """index_dict: {index_name: RowGroupIndexBase} -> set of row-group piece ordinals."""
        raise NotImplementedError


class SingleIndexSelector(RowGroupSelectorBase):
    """Row groups containing any of ``values`` per one index (reference ~L30)."""

    def __init__(self, index_name, values):
        self._index_name = index_name
        self._values = list(values)

    def get_index_names(self):
        return [self._index_name]

    def select_row_groups(self, index_dict):
        indexer = index_dict.get(self._index_name)
        if indexer is None:
            raise ValueError("Dataset has no index named %r" % self._index_name)
        selected = set()
        for value in self._values:
            selected |= set(indexer.get_row_group_indexes(value))
        return selected


class IntersectIndexSelector(RowGroupSelectorBase):
    """Row groups selected by ALL child selectors."""

    def __init__(self, single_index_selectors):
        self._selectors = list(single_index_selectors)

    def get_index_names(self):
        names = []
        for s in self._selectors:
            names.extend(s.get_index_names())
        return names

    def select_row_groups(self, index_dict):
        sets = [s.select_row_groups(index_dict) for s in self._selectors]
        return set.intersection(*sets) if sets else set()


class UnionIndexSelector(RowGroupSelectorBase):
    """Row groups selected by ANY child selector."""

    def __init__(self, single_index_selectors):
        self._selectors = list(single_index_selectors)

    def get_index_names(self):
        names = []
        for s in self._selectors:
            names.extend(s.get_index_names())
        return names

    def select_row_groups(self, index_dict):
        selected = set()
        for s in self._selectors:
            selected |= s.select_row_groups(index_dict)
        return selected
