"""Spark DataFrame → TF / Torch / JAX loaders with a materialized Parquet cache.

Capability parity with petastorm/spark/spark_dataset_converter.py (``SparkDatasetConverter``
~L120: ``make_tf_dataset`` ~L200, ``make_torch_dataloader`` ~L300, ``delete``;
``make_spark_converter`` ~L400: plan-hash cache, atexit GC, precision normalization), plus
the TPU-native ``make_jax_dataloader`` that yields sharded ``jax.Array`` batches.

pyspark is imported lazily; every entry point raises a clear error when it is absent
(this image ships no pyspark — the pyarrow-native path for the same workflow is
``petastorm_tpu.metadata.write_dataset`` + ``make_batch_reader``).
"""
from __future__ import annotations

import atexit
import hashlib
import logging
import posixpath
import threading
import uuid

logger = logging.getLogger(__name__)

_CACHE_DIR_CONF = "petastorm.spark.converter.parentCacheDirUrl"

_materialized: dict = {}  # cache key -> SparkDatasetConverter
_materialized_lock = threading.Lock()
_delete_handler = None


def _require_pyspark():
    try:
        import pyspark  # noqa: F401

        return pyspark
    except ImportError as e:
        raise ImportError(
            "petastorm_tpu.spark requires pyspark, which is not installed. For a "
            "Spark-free equivalent workflow, write Parquet with "
            "petastorm_tpu.metadata.write_dataset (or any Parquet writer) and read with "
            "make_batch_reader / petastorm_tpu.loader.make_dataloader."
        ) from e


#: DataFrame protocol the converter actually consumes. Anything satisfying it works —
#: a real pyspark DataFrame, a pyspark-connect proxy, or the fake-session contract
#: fixtures in tests/test_spark_contract.py (pyspark is not installed in this image;
#: see BASELINE.md "Environment constraints").
_DATAFRAME_PROTOCOL = ("sparkSession", "schema", "write", "count")


def _require_pyspark_or_compatible(df):
    try:
        import pyspark  # noqa: F401

        return
    except ImportError:
        if all(hasattr(df, attr) for attr in _DATAFRAME_PROTOCOL):
            return  # duck-typed DataFrame: the converter only uses the protocol above
    _require_pyspark()


def register_delete_dir_handler(handler):
    """Override how cache dirs are deleted (reference ``register_delete_dir_handler``)."""
    global _delete_handler
    _delete_handler = handler


def _delete_dir(url):
    if _delete_handler is not None:
        _delete_handler(url)
        return
    from petastorm_tpu.fs import get_filesystem_and_path_or_paths

    fs, path = get_filesystem_and_path_or_paths(url)
    fs.delete_dir_contents(path, accept_root_dir=True, missing_dir_ok=True)
    try:
        fs.delete_dir(path)
    except Exception as e:  # noqa: BLE001 - already gone / root kept
        logger.debug("delete_dir(%s) after contents cleanup: %s", path, e)


class SparkDatasetConverter:
    """Handle to a materialized dataset: build TF/Torch/JAX loaders over it.

    Reference contract kept: ``PARENT_CACHE_DIR_URL_CONF``, ``dataset_size``, context-manager
    loaders, ``delete()``.
    """

    PARENT_CACHE_DIR_URL_CONF = _CACHE_DIR_CONF

    def __init__(self, cache_dir_url, file_urls, dataset_size):
        self.cache_dir_url = cache_dir_url
        self.file_urls = file_urls
        self._dataset_size = dataset_size

    def __len__(self):
        return self._dataset_size

    # -- loader factories --------------------------------------------------------------

    def make_jax_dataloader(self, batch_size=32, sharding=None, num_epochs=1,
                            shuffling_queue_capacity=0, **reader_kwargs):
        """TPU-native loader: sharded ``jax.Array`` batches (the reference has no analog)."""
        from petastorm_tpu.loader import make_dataloader

        return make_dataloader(self.file_urls, batch_size=batch_size, sharding=sharding,
                               num_epochs=num_epochs,
                               shuffling_queue_capacity=shuffling_queue_capacity,
                               **reader_kwargs)

    def make_torch_dataloader(self, batch_size=32, num_epochs=1,
                              shuffling_queue_capacity=0, cur_shard=None, shard_count=None,
                              **reader_kwargs):
        """Context manager yielding a torch ``BatchedDataLoader`` (reference ~L300)."""
        return _TorchDatasetContextManager(self.file_urls, batch_size, num_epochs,
                                           shuffling_queue_capacity, cur_shard,
                                           shard_count, reader_kwargs)

    def make_tf_dataset(self, batch_size=None, num_epochs=1, cur_shard=None,
                        shard_count=None, **reader_kwargs):
        """Context manager yielding a ``tf.data.Dataset`` (reference ~L200)."""
        return _TfDatasetContextManager(self.file_urls, batch_size, num_epochs,
                                        cur_shard, shard_count, reader_kwargs)

    def delete(self):
        """Delete the materialized cache dir and forget the cache entry."""
        with _materialized_lock:
            for key, conv in list(_materialized.items()):
                if conv is self:
                    del _materialized[key]
        _delete_dir(self.cache_dir_url)


class _TorchDatasetContextManager:
    def __init__(self, file_urls, batch_size, num_epochs, shuffling_queue_capacity,
                 cur_shard, shard_count, reader_kwargs):
        self._args = (file_urls, batch_size, num_epochs, shuffling_queue_capacity,
                      cur_shard, shard_count, reader_kwargs)
        self._loader = None

    def __enter__(self):
        from petastorm_tpu.adapters.pytorch import BatchedDataLoader
        from petastorm_tpu.reader import make_batch_reader

        (urls, batch_size, num_epochs, cap, cur_shard, shard_count, kw) = self._args
        reader = make_batch_reader(urls, num_epochs=num_epochs, cur_shard=cur_shard,
                                   shard_count=shard_count, **kw)
        self._loader = BatchedDataLoader(reader, batch_size=batch_size,
                                         shuffling_queue_capacity=cap)
        return self._loader

    def __exit__(self, exc_type, exc, tb):
        self._loader.stop()
        self._loader.join()


class _TfDatasetContextManager:
    def __init__(self, file_urls, batch_size, num_epochs, cur_shard, shard_count,
                 reader_kwargs):
        self._args = (file_urls, batch_size, num_epochs, cur_shard, shard_count,
                      reader_kwargs)
        self._reader = None

    def __enter__(self):
        from petastorm_tpu.adapters.tf import make_petastorm_dataset
        from petastorm_tpu.reader import make_batch_reader

        urls, batch_size, num_epochs, cur_shard, shard_count, kw = self._args
        self._reader = make_batch_reader(urls, num_epochs=num_epochs,
                                         cur_shard=cur_shard, shard_count=shard_count, **kw)
        ds = make_petastorm_dataset(self._reader)
        if batch_size:
            ds = ds.unbatch().batch(batch_size)
        return ds

    def __exit__(self, exc_type, exc, tb):
        self._reader.stop()
        self._reader.join()


def _normalize_precision(df, dtype):
    """float64→float32 (or as asked) normalization before materialization (reference).

    With pyspark absent, falls back to the protocol form: columns whose
    ``dataType.typeName()`` is the source type are re-cast via
    ``df.withColumn(name, df[name].cast(target_typename))`` — the exact calls a real
    DataFrame would see, so the fake-session contract tests assert them.
    """
    if dtype is None:
        return df
    target_name = {"float32": "float", "float64": "double"}[dtype]
    source_name = "double" if dtype == "float32" else "float"
    try:
        from pyspark.sql.functions import col
        from pyspark.sql.types import DoubleType, FloatType

        target = FloatType() if dtype == "float32" else DoubleType()
        source = DoubleType() if dtype == "float32" else FloatType()
        for field in df.schema.fields:
            if field.dataType == source:
                df = df.withColumn(field.name, col(field.name).cast(target))
        return df
    except ImportError:
        for field in df.schema.fields:
            type_name = getattr(field.dataType, "typeName", lambda: None)()
            if type_name == source_name:
                df = df.withColumn(field.name, df[field.name].cast(target_name))
        return df


def _df_plan_string(df):
    """Stable textual identity of the DataFrame's analyzed plan (cache key basis)."""
    jdf = getattr(df, "_jdf", None)
    if jdf is not None:
        try:
            return jdf.queryExecution().analyzed().toString()
        except Exception as e:  # noqa: BLE001 - connect/duck-typed frames:
            # fall through to the weaker identities — counted (GL-O002), since
            # a degraded cache key can silently re-materialize datasets
            from petastorm_tpu.obs.log import degradation

            degradation("spark_plan_identity",
                        "DataFrame plan identity unavailable (%s); falling "
                        "back to semanticHash/schema cache keying", e)
    semantic_hash = getattr(df, "semanticHash", None)
    if callable(semantic_hash):
        return "semanticHash:%s" % semantic_hash()
    # No plan identity at all: schema alone is NOT content identity — two frames over
    # different data with equal schemas would share a cache entry and silently serve
    # the wrong materialized rows. Refuse instead.
    raise ValueError(
        "Cannot derive a cache identity for %r: it exposes neither _jdf (pyspark) nor "
        "semanticHash(). Implement semanticHash() on the DataFrame, or bypass the "
        "converter cache by materializing manually (petastorm_tpu.metadata.write_dataset "
        "+ make_batch_reader)." % type(df).__name__
    )


def _df_cache_key(df, parent_dir, compression_codec, dtype):
    plan = _df_plan_string(df)
    payload = "|".join([plan, parent_dir or "", compression_codec or "", dtype or ""])
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()


def make_spark_converter(df, parquet_row_group_size_bytes=32 * 1024 * 1024,
                         compression_codec=None, dtype="float32"):
    """Materialize ``df`` under the configured parent cache dir and return a converter.

    Cache keyed by (analyzed plan, options): re-converting the same DataFrame reuses the
    materialized files (reference ``make_spark_converter`` ~L400).
    """
    _require_pyspark_or_compatible(df)
    spark = df.sparkSession
    parent = spark.conf.get(_CACHE_DIR_CONF, None)
    if not parent:
        raise ValueError(
            "Configure the parent cache dir first: spark.conf.set(%r, <dir url>)"
            % _CACHE_DIR_CONF
        )
    df = _normalize_precision(df, dtype)
    key = _df_cache_key(df, parent, compression_codec, dtype)
    with _materialized_lock:
        cached = _materialized.get(key)
    if cached is not None:
        return cached

    cache_dir_url = posixpath.join(parent, "%s" % uuid.uuid4().hex)
    writer = df.write.mode("overwrite") \
        .option("parquet.block.size", parquet_row_group_size_bytes)
    if compression_codec:
        writer = writer.option("compression", compression_codec)
    writer.parquet(cache_dir_url)

    from petastorm_tpu.fs import get_filesystem_and_path_or_paths

    fs, path = get_filesystem_and_path_or_paths(cache_dir_url)
    from petastorm_tpu.metadata import _list_parquet_files

    files = _list_parquet_files(fs, path)
    size = df.count()
    converter = SparkDatasetConverter(cache_dir_url, cache_dir_url, size)
    with _materialized_lock:
        _materialized[key] = converter
    atexit.register(_atexit_delete, converter)
    logger.info("Materialized %d rows to %s (%d files)", size, cache_dir_url, len(files))
    return converter


def _atexit_delete(converter):
    try:
        converter.delete()
    except Exception:  # noqa: BLE001 - best-effort GC at interpreter exit
        logger.warning("Failed to delete converter cache %s", converter.cache_dir_url)
