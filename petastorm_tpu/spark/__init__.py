"""spark subpackage."""
