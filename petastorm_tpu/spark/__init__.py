"""Spark converter API (reference petastorm/spark/__init__.py re-exports)."""

from petastorm_tpu.spark.spark_dataset_converter import (  # noqa: F401
    SparkDatasetConverter,
    make_spark_converter,
    register_delete_dir_handler,
)
