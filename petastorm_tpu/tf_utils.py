"""Migration alias: the reference exposes its TF adapters as ``petastorm.tf_utils``
(petastorm/tf_utils.py); users switching frameworks keep their import path —
``from petastorm_tpu.tf_utils import make_petastorm_dataset, tf_tensors``.

Canonical home: :mod:`petastorm_tpu.adapters.tf`.
"""
from petastorm_tpu.adapters.tf import (  # noqa: F401
    make_petastorm_dataset,
    tf_tensors,
)
