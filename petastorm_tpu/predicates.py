"""Row-level predicates evaluated in workers before full decode.

Capability parity with the reference predicate set (petastorm/predicates.py: ``PredicateBase``
~L30, ``in_set``, ``in_intersection``, ``in_negate``, ``in_reduce``, ``in_lambda`` ~L90,
``in_pseudorandom_split`` ~L140). ``get_fields()`` declares the columns a predicate needs so
workers read only those columns first and fetch the remaining columns only for matching rows.

TPU delta: ``do_include_vectorized`` lets a predicate evaluate a whole column batch at once
(numpy arrays) — the batch reader path uses it to mask Arrow record batches without a Python
loop; the default falls back to per-row ``do_include``.
"""
from __future__ import annotations

import hashlib

import numpy as np


class PredicateBase:
    def get_fields(self):
        """Names of the fields this predicate reads."""
        raise NotImplementedError

    def do_include(self, values):
        """values: {field_name: value} for one row -> bool."""
        raise NotImplementedError

    def do_include_vectorized(self, columns):
        """columns: {field_name: np.ndarray} -> boolean mask. Default: per-row loop."""
        names = list(columns.keys())
        n = len(columns[names[0]]) if names else 0
        mask = np.empty(n, dtype=bool)
        for i in range(n):
            mask[i] = bool(self.do_include({name: columns[name][i] for name in names}))
        return mask


class in_set(PredicateBase):  # noqa: N801 - reference naming kept
    """True when the field value is in ``values``."""

    def __init__(self, values, predicate_field):
        self._values = set(values)
        self._field = predicate_field

    def get_fields(self):
        return {self._field}

    def do_include(self, values):
        return values[self._field] in self._values

    def do_include_vectorized(self, columns):
        return np.isin(columns[self._field], list(self._values))


class in_intersection(PredicateBase):  # noqa: N801
    """True when the field (a collection) intersects ``values``."""

    def __init__(self, values, predicate_field):
        self._values = set(values)
        self._field = predicate_field

    def get_fields(self):
        return {self._field}

    def do_include(self, values):
        return bool(self._values.intersection(values[self._field]))

    def do_include_vectorized(self, columns):
        # rows are ragged collections (object column); the per-row set intersection is
        # inherent, but skip the base class's per-row dict construction
        vals = self._values
        col = columns[self._field]
        return np.fromiter((bool(vals.intersection(v)) for v in col),
                           dtype=bool, count=len(col))


class in_negate(PredicateBase):  # noqa: N801
    def __init__(self, predicate):
        self._predicate = predicate

    def get_fields(self):
        return self._predicate.get_fields()

    def do_include(self, values):
        return not self._predicate.do_include(values)

    def do_include_vectorized(self, columns):
        return ~np.asarray(self._predicate.do_include_vectorized(columns), dtype=bool)


class in_reduce(PredicateBase):  # noqa: N801
    """Combine predicates with a reduction (e.g. ``all``/``any`` or numpy logical ops)."""

    def __init__(self, predicate_list, reduce_func):
        self._predicates = list(predicate_list)
        self._reduce_func = reduce_func

    def get_fields(self):
        fields = set()
        for p in self._predicates:
            fields |= set(p.get_fields())
        return fields

    def do_include(self, values):
        return self._reduce_func([p.do_include(values) for p in self._predicates])

    def do_include_vectorized(self, columns):
        masks = [np.asarray(p.do_include_vectorized(columns), dtype=bool)
                 for p in self._predicates]
        # The builtin all/any users pass for the per-row path are ambiguous over
        # arrays — translate them to their elementwise equivalents
        if self._reduce_func in (all, np.all):
            return np.logical_and.reduce(masks)
        if self._reduce_func in (any, np.any):
            return np.logical_or.reduce(masks)
        return np.asarray(self._reduce_func(masks), dtype=bool)


def implied_dnf_filters(predicate):
    """DNF filter clauses IMPLIED by ``predicate`` (predicate ⇒ clauses), or None.

    Used for plan-time pruning only: the reader conjoins these with any user
    ``filters`` so hive-partition and row-group-statistics pruning fire for the
    translatable predicate families too — ``in_set`` (→ ``in``), ``in_negate(in_set)``
    (→ ``not in``), and ``in_reduce`` over ``all``/``any``. The predicate itself still
    runs as the row-level mask, so an over-broad translation can never change
    results — untranslatable predicates (``in_lambda``, ``in_pseudorandom_split``,
    ``in_intersection``) just return None (no extra pruning). The reference prunes
    row groups for predicates only through prebuilt indexes (``rowgroup_selector``,
    petastorm/selectors.py ~L30); this derives the pruning automatically.

    Returns the OR-of-ANDs form ``[[(field, op, value-list), ...], ...]``.
    """
    if isinstance(predicate, in_set):
        return [[(predicate._field, "in", sorted(predicate._values, key=repr))]]
    if isinstance(predicate, in_negate):
        inner = predicate._predicate
        if isinstance(inner, in_set):
            return [[(inner._field, "not in", sorted(inner._values, key=repr))]]
        return None
    if isinstance(predicate, in_reduce):
        # Pruning is optional (the row mask carries correctness), so bail out rather
        # than let nested reduces cross-product into an exponential clause set.
        max_clauses = 64
        children = [implied_dnf_filters(p) for p in predicate._predicates]
        if predicate._reduce_func in (all, np.all, np.logical_and.reduce):
            # AND: untranslatable children drop out (a conjunct subset is still
            # implied); cross-product the survivors' or-clauses
            out = [[]]
            for c in children:
                if c is None:
                    continue
                out = [acc + clause for acc in out for clause in c]
                if len(out) > max_clauses:
                    return None
            return out if out != [[]] else None
        if predicate._reduce_func in (any, np.any, np.logical_or.reduce):
            # OR: every child must translate, else rows outside the union can match
            if any(c is None for c in children):
                return None
            out = [clause for c in children for clause in c]
            return out if len(out) <= max_clauses else None
        return None
    return None


class in_lambda(PredicateBase):  # noqa: N801
    """Arbitrary user function over declared fields (reference ~L90).

    ``func({field: value}) -> bool``; optional ``vectorized_func({field: array}) -> mask``.
    """

    def __init__(self, predicate_fields, func, vectorized_func=None):
        self._fields = list(predicate_fields)
        self._func = func
        self._vectorized_func = vectorized_func

    def get_fields(self):
        return set(self._fields)

    def do_include(self, values):
        return self._func(values)

    def do_include_vectorized(self, columns):
        if self._vectorized_func is not None:
            return np.asarray(self._vectorized_func(columns), dtype=bool)
        return super().do_include_vectorized(columns)


class in_pseudorandom_split(PredicateBase):  # noqa: N801
    """Deterministic hash-based train/val/test split (reference ~L140).

    ``fraction_list`` sums to <= 1; ``subset_index`` selects which band a row must hash into.
    The split is a pure function of the field value, so it is stable across runs and hosts.
    """

    def __init__(self, fraction_list, subset_index, predicate_field):
        if not 0 <= subset_index < len(fraction_list):
            raise ValueError("subset_index out of range")
        if sum(fraction_list) > 1.0 + 1e-9:
            raise ValueError("fractions must sum to <= 1")
        self._fractions = list(fraction_list)
        self._subset_index = subset_index
        self._field = predicate_field
        self._lo = sum(fraction_list[:subset_index])
        self._hi = self._lo + fraction_list[subset_index]

    def get_fields(self):
        return {self._field}

    @staticmethod
    def _unit_hash(value):
        digest = hashlib.md5(str(value).encode("utf-8")).hexdigest()[:8]
        return int(digest, 16) / float(0xFFFFFFFF)

    def do_include(self, values):
        u = self._unit_hash(values[self._field])
        return self._lo <= u < self._hi

    def do_include_vectorized(self, columns):
        """Hash each UNIQUE value once and map back through the inverse index — on
        categorical split keys (user ids etc.) this collapses the md5 loop to the
        distinct values; the md5 itself must stay per-value to keep split semantics
        identical to ``do_include``."""
        col = np.asarray(columns[self._field])
        try:
            uniq, inverse = np.unique(col, return_inverse=True)
        except TypeError:  # unorderable mixed objects
            uniq, inverse = col, np.arange(len(col))
        md5 = hashlib.md5
        # int.from_bytes(digest[:4]) == int(hexdigest[:8], 16): same unit interval value
        units = np.fromiter(
            (int.from_bytes(md5(str(v).encode("utf-8")).digest()[:4], "big")
             for v in uniq),
            dtype=np.uint32, count=len(uniq),
        ).astype(np.float64) / float(0xFFFFFFFF)
        mask = (self._lo <= units) & (units < self._hi)
        return mask[inverse]
