"""Tiny MNIST convnet — the hello_world acceptance model (reference examples/mnist)."""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train=True):
        if x.ndim == 3:
            x = x[..., None]  # (b, 28, 28) -> NHWC
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
