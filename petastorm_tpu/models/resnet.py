"""ResNet family (v1.5 bottleneck) in flax.linen — the flagship image model.

Petastorm's headline workload is feeding ImageNet/ResNet-50 training (examples/imagenet,
BASELINE.json north-star: ResNet-50 on ImageNet-Parquet); the reference ships no model code,
so this is the acceptance-config model our data plane is measured against. TPU notes: NHWC
layout (XLA's native conv layout on TPU), bfloat16 compute with float32 batch-norm stats and
params, batch stats folded for inference via ``mutable``.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    """Two 3x3 convs — the ResNet18/34 block (He et al. 2015, table 1)."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 (self.strides, self.strides), name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 (self.strides, self.strides), name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    block_cls: ModuleDef = BottleneckBlock

    @nn.compact
    def __call__(self, x, train=True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = functools.partial(nn.BatchNorm, use_running_average=not train,
                                 momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        act = nn.relu

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(self.num_filters * 2 ** i, strides,
                                   conv=conv, norm=norm, act=act)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3])
