"""SPMD MoE transformer: the flagship multi-parallel training step.

The reference ships no model code (it's a data library); this model exists to exercise and
validate the full TPU parallelism surface this framework feeds (SURVEY.md §3.7): every batch
from the DataLoader can be consumed by a training step sharded over

- **dp** — batch split; gradients all-reduced over (dp, sp),
- **pp** — GPipe microbatch pipeline over stage-stacked layer params
  (:func:`petastorm_tpu.parallel.pipeline.spmd_pipeline`, ppermute hops),
- **sp** — sequence split with ring attention
  (:func:`petastorm_tpu.parallel.attention.ring_attention`),
- **tp** — Megatron-style column/row-parallel projections (heads and FFN hidden split;
  one psum per block),
- **ep** — expert parallelism: top-1 gated MoE, tokens routed to expert shards with a
  pair of ``lax.all_to_all`` (GShard-style static-capacity dispatch einsums — no dynamic
  shapes, MXU-friendly).

Everything runs inside ONE ``jax.shard_map`` over the whole mesh (fully-manual SPMD, the
scaling-book recipe): collectives are explicit, XLA schedules them onto ICI.
"""
from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from petastorm_tpu.parallel.attention import ring_attention
from petastorm_tpu.parallel.pipeline import spmd_pipeline


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 64
    n_heads: int = 8
    head_dim: int = 16
    d_ff: int = 128
    n_stages: int = 2          # pipeline depth (== mesh pp size)
    layers_per_stage: int = 1
    n_experts: int = 4
    capacity_factor: float = 2.0
    max_seq: int = 256
    dtype: Any = jnp.float32   # bfloat16 on real TPU


def init_params(cfg, key):
    """Global (unsharded) parameter pytree; stage-stacked arrays lead with n_stages."""
    k = iter(jax.random.split(key, 16))
    s, L = cfg.n_stages, cfg.layers_per_stage
    d, H, hd, f, E = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff, cfg.n_experts
    init = lambda kk, shape, scale: (jax.random.normal(kk, shape, jnp.float32)
                                     * scale).astype(cfg.dtype)
    return {
        "embed": init(next(k), (cfg.vocab, d), 0.02),
        "pos": init(next(k), (cfg.max_seq, d), 0.02),
        "stages": {
            "ln1": jnp.ones((s, L, d), cfg.dtype),
            "wqkv": init(next(k), (s, L, d, 3, H, hd), d ** -0.5),
            "wo": init(next(k), (s, L, H, hd, d), (H * hd) ** -0.5),
            "ln2": jnp.ones((s, L, d), cfg.dtype),
            "wg": init(next(k), (s, L, d, E), 0.02),
            "w1": init(next(k), (s, L, E, d, f), d ** -0.5),
            "w2": init(next(k), (s, L, E, f, d), f ** -0.5),
        },
        "ln_f": jnp.ones((cfg.d_model,), cfg.dtype),
        "unembed": init(next(k), (d, cfg.vocab), d ** -0.5),
    }


def model_mesh(axis_sizes=None, devices=None):
    """Mesh for this model: always declares all five axes (size 1 where unused) so the
    sharded step's collectives are well-formed regardless of which axes actually split."""
    from petastorm_tpu.parallel.mesh import make_mesh

    sizes = {"pp": 1, "ep": 1, "sp": 1, "tp": 1}
    sizes.update(axis_sizes or {})
    return make_mesh(sizes, devices=devices)


def param_shardings(cfg, mesh):
    """NamedShardings: stages over pp; heads/ffn-hidden over tp; experts over ep.

    The mesh must declare all of dp/pp/ep/sp/tp (use :func:`model_mesh`)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    missing = {"dp", "pp", "ep", "sp", "tp"} - set(mesh.axis_names)
    if missing:
        raise ValueError("mesh is missing axes %s; build it with model_mesh()" % sorted(missing))

    ns = lambda *spec: NamedSharding(mesh, P(*spec))
    return {
        "embed": ns(), "pos": ns(), "ln_f": ns(), "unembed": ns(),
        "stages": {
            "ln1": ns("pp"),
            "wqkv": ns("pp", None, None, None, "tp", None),
            "wo": ns("pp", None, "tp", None, None),
            "ln2": ns("pp"),
            "wg": ns("pp"),
            "w1": ns("pp", None, "ep", None, "tp"),
            "w2": ns("pp", None, "ep", "tp", None),
        },
    }


def _rms_norm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * lax.rsqrt(var + 1e-6).astype(x.dtype)) * scale


def _attention_block(x, ln, wqkv, wo, cfg):
    """Ring attention over sp; heads local to the tp rank (column/row parallel)."""
    h = _rms_norm(x, ln)
    qkv = jnp.einsum("bsd,dthe->bsthe", h, wqkv)  # t=3, h=H_local, e=head_dim
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    o = ring_attention(q, k, v, axis_name="sp", causal=True)
    out = jnp.einsum("bshe,hed->bsd", o, wo)
    return x + lax.psum(out, ("tp",))


def _moe_block(x, ln, wg, w1, w2, cfg, ep_size, tp_size):
    """Top-1 expert-parallel MoE with static capacity (GShard dispatch einsums).

    Tokens are split over the ``ep`` axis (each rank gates its own T/ep slice), expert
    inputs are exchanged with an ``all_to_all`` pair, and per-rank outputs reassemble via
    scatter + ``psum`` — whose AD transpose is a plain slice, so replicated-parameter
    gradients are exact (an all_gather here would overcount by ep under transposition).
    """
    b, s, d = x.shape
    h_full = _rms_norm(x, ln).reshape(b * s, d)
    T, E = h_full.shape[0], cfg.n_experts
    if T % ep_size:
        raise ValueError("local tokens %d not divisible by ep=%d" % (T, ep_size))
    T_loc = T // ep_size
    if ep_size > 1:
        ep_idx = lax.axis_index("ep")
        h = lax.dynamic_slice(h_full, (ep_idx * T_loc, jnp.int32(0)), (T_loc, d))
    else:
        h = h_full
    C = max(1, int(math.ceil(T_loc / E * cfg.capacity_factor)))

    gates = jax.nn.softmax(jnp.einsum("td,de->te", h, wg).astype(jnp.float32), axis=-1)
    eidx = jnp.argmax(gates, axis=-1)
    onehot = jax.nn.one_hot(eidx, E, dtype=jnp.float32)                   # (T_loc, E)
    pos_in_e = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot
    keep = onehot * (pos_in_e < C)                                        # capacity drop
    dispatch = keep[..., None] * jax.nn.one_hot(
        pos_in_e.astype(jnp.int32), C, dtype=jnp.float32)                 # (T_loc, E, C)
    gate_val = jnp.sum(gates * keep, axis=-1)                             # (T_loc,)

    expert_in = jnp.einsum("tec,td->ecd", dispatch, h.astype(jnp.float32))  # (E, C, d)
    if ep_size > 1:
        # split E over ep ranks; gather every rank's token slots for my local experts
        expert_in = lax.all_to_all(expert_in, "ep", split_axis=0, concat_axis=1,
                                   tiled=True)                            # (E_loc, C*ep, d)
    expert_in = expert_in.astype(cfg.dtype)
    hidden = jnp.einsum("ecd,edf->ecf", expert_in, w1)                    # f = f_local (tp)
    hidden = jax.nn.relu(hidden)
    out = jnp.einsum("ecf,efd->ecd", hidden, w2)
    out = lax.psum(out, ("tp",))                                          # row-parallel FFN
    if ep_size > 1:
        out = lax.all_to_all(out, "ep", split_axis=1, concat_axis=0, tiled=True)  # (E, C, d)
    y = jnp.einsum("tec,ecd->td", dispatch, out.astype(jnp.float32))
    y = y * gate_val[:, None]                                             # (T_loc, d)
    if ep_size > 1:
        placed = jnp.zeros((T, d), jnp.float32)
        placed = lax.dynamic_update_slice(placed, y, (ep_idx * T_loc, jnp.int32(0)))
        y = lax.psum(placed, ("ep",))                                     # (T, d), ep-invariant
    else:
        # params are typed ep-varying even on a size-1 axis; the identity psum restores an
        # ep-invariant activation so the layer-scan carry type is stable
        y = lax.psum(y, ("ep",))
    return x + y.reshape(b, s, d).astype(x.dtype)


def _make_stage_fn(cfg, ep_size, tp_size):
    """stage_fn(stage_params, x) scanning the stage's local layer stack."""

    def layer(x, lp):
        x = _attention_block(x, lp["ln1"], lp["wqkv"], lp["wo"], cfg)
        x = _moe_block(x, lp["ln2"], lp["wg"], lp["w1"], lp["w2"], cfg, ep_size, tp_size)
        return x, None

    def stage_fn(stage_params, x):
        x, _ = lax.scan(lambda h, lp: layer(h, lp), x, stage_params)
        return x

    return stage_fn


def make_train_step(cfg, mesh, n_micro=2, learning_rate=1e-2):
    """jitted ``train_step(params, tokens, targets) -> (params, loss)``.

    ``tokens``/``targets``: (batch, seq) int32, batch sharded dp, seq sharded sp
    (``parallel.mesh.sequence_sharding``). Params laid out per :func:`param_shardings`.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    ep_size = mesh.shape.get("ep", 1)
    tp_size = mesh.shape.get("tp", 1)
    pp_size = mesh.shape.get("pp", 1)
    if cfg.n_stages != pp_size:
        raise ValueError(
            "cfg.n_stages (%d) must equal the mesh pp size (%d): spmd_pipeline assigns "
            "exactly one stage per pp rank" % (cfg.n_stages, pp_size)
        )
    if cfg.n_heads % tp_size or cfg.d_ff % tp_size or cfg.n_experts % ep_size:
        raise ValueError("heads/d_ff/experts must divide tp/ep mesh sizes")
    stage_fn = _make_stage_fn(cfg, ep_size, tp_size)

    def local_loss(params, tokens, targets):
        # tokens: (b_local, s_local); embed + absolute positions (global via sp index)
        b_loc, s_loc = tokens.shape
        sp_idx = lax.axis_index("sp")
        x = params["embed"][tokens]
        pos = lax.dynamic_slice(params["pos"], (sp_idx * s_loc, 0),
                                (s_loc, params["pos"].shape[1]))
        x = x + pos[None]
        if b_loc % n_micro:
            raise ValueError("local batch %d not divisible by n_micro=%d" % (b_loc, n_micro))
        xm = x.reshape((n_micro, b_loc // n_micro, s_loc, cfg.d_model))
        ym = spmd_pipeline(stage_fn, params["stages"], xm, "pp")
        y = ym.reshape((b_loc, s_loc, cfg.d_model))
        y = _rms_norm(y, params["ln_f"])
        logits = jnp.einsum("bsd,dv->bsv", y, params["unembed"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        loss_sum = jnp.sum(nll)
        count = jnp.float32(b_loc * s_loc)
        # global mean over the data axes (batch × sequence partitions)
        return lax.psum(loss_sum, ("dp", "sp")) / lax.psum(count, ("dp", "sp"))

    def sharded_step(params, tokens, targets):
        loss, grads = jax.value_and_grad(local_loss)(params, tokens, targets)
        new_params = jax.tree.map(lambda p, g: p - learning_rate * g.astype(p.dtype),
                                  params, grads)
        return new_params, loss

    pspecs = jax.tree.map(lambda s: s.spec, param_shardings(cfg, mesh),
                          is_leaf=lambda x: hasattr(x, "spec"))
    data_spec = P("dp", "sp")
    from petastorm_tpu.compat import shard_map

    step = shard_map()(
        sharded_step, mesh=mesh,
        in_specs=(pspecs, data_spec, data_spec),
        out_specs=(pspecs, P()),
    )
    return jax.jit(step)


def data_sharding(mesh):
    """Sharding the DataLoader should use for (batch, seq) token batches of this model."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P("dp", "sp"))


def reference_loss(cfg, params, tokens, targets, n_micro=2):
    """Dense single-device oracle replicating the sharded forward exactly (for tests)."""
    x = params["embed"][tokens] + params["pos"][None, : tokens.shape[1]]
    h = x
    for s in range(cfg.n_stages):
        for l in range(cfg.layers_per_stage):
            lp = {k: v[s, l] for k, v in params["stages"].items()}
            hn = _rms_norm(h, lp["ln1"])
            qkv = jnp.einsum("bsd,dthe->bsthe", hn, lp["wqkv"])
            from petastorm_tpu.parallel.attention import reference_attention

            o = reference_attention(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2], causal=True)
            h = h + jnp.einsum("bshe,hed->bsd", o, lp["wo"])
            # dense MoE with the same static capacity semantics
            b, sq, d = h.shape
            hm = _rms_norm(h, lp["ln2"]).reshape(b * sq, d)
            T, E = hm.shape[0], cfg.n_experts
            C = max(1, int(math.ceil(T / E * cfg.capacity_factor)))
            gates = jax.nn.softmax(
                jnp.einsum("td,de->te", hm, lp["wg"]).astype(jnp.float32), -1)
            eidx = jnp.argmax(gates, -1)
            onehot = jax.nn.one_hot(eidx, E, dtype=jnp.float32)
            pos_in_e = (jnp.cumsum(onehot, 0) - 1.0) * onehot
            keep = onehot * (pos_in_e < C)
            dispatch = keep[..., None] * jax.nn.one_hot(
                pos_in_e.astype(jnp.int32), C, dtype=jnp.float32)
            gate_val = jnp.sum(gates * keep, -1)
            ein = jnp.einsum("tec,td->ecd", dispatch, hm.astype(jnp.float32)).astype(cfg.dtype)
            hid = jax.nn.relu(jnp.einsum("ecd,edf->ecf", ein, lp["w1"]))
            out = jnp.einsum("ecf,efd->ecd", hid, lp["w2"])
            y = jnp.einsum("tec,ecd->td", dispatch, out.astype(jnp.float32)) * gate_val[:, None]
            h = h + y.reshape(b, sq, d).astype(h.dtype)
    y = _rms_norm(h, params["ln_f"])
    logits = jnp.einsum("bsd,dv->bsv", y, params["unembed"]).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, -1)
    nll = -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
    return jnp.mean(nll)
