"""Vision Transformer (ViT) in flax.linen — second flagship image family.

The reference ships no model code; the model zoo exists to exercise the data plane
against the acceptance configs (BASELINE.json), and ViT is the other half of the
ImageNet story next to ResNet: patchify turns the loader's (n, h, w, 3) uint8
batches into (n, tokens, d) sequences, so the same pipeline feeds both conv and
attention consumers. TPU notes: bfloat16 compute with float32 layer norms and
params, einsum attention (MXU-friendly), no data-dependent control flow.
"""
from __future__ import annotations

import functools
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MlpBlock(nn.Module):
    mlp_dim: int
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, deterministic=True):
        d = x.shape[-1]
        x = nn.Dense(self.mlp_dim, dtype=self.dtype)(x)
        x = nn.gelu(x)
        x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)
        return nn.Dense(d, dtype=self.dtype)(x)


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, deterministic=True):
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        y = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads, dtype=self.dtype,
            dropout_rate=self.dropout_rate)(y, y, deterministic=deterministic)
        x = x + y
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        return x + MlpBlock(self.mlp_dim, self.dropout_rate,
                            self.dtype)(y, deterministic=deterministic)


class ViT(nn.Module):
    """ViT classifier: patchify → [cls] + learned positions → encoder → head.

    Defaults are ViT-B/16 (Dosovitskiy et al. 2020 table 1): 12 layers, width 768,
    12 heads, MLP 3072 — 86.6M params at 224² with 1000 classes.
    """

    num_classes: int = 1000
    patch_size: int = 16
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        n, h, w, _c = x.shape
        p = self.patch_size
        x = x.astype(self.dtype)
        # patchify as one conv: MXU matmul over p*p*c per output token
        x = nn.Conv(self.hidden_size, (p, p), strides=(p, p), padding="VALID",
                    dtype=self.dtype, name="embedding")(x)
        x = x.reshape(n, -1, self.hidden_size)
        cls = self.param("cls", nn.initializers.zeros, (1, 1, self.hidden_size),
                         jnp.float32)
        x = jnp.concatenate([jnp.broadcast_to(cls.astype(self.dtype),
                                              (n, 1, self.hidden_size)), x], axis=1)
        pos = self.param("pos_embedding", nn.initializers.normal(stddev=0.02),
                         (1, x.shape[1], self.hidden_size), jnp.float32)
        x = x + pos.astype(self.dtype)
        x = nn.Dropout(self.dropout_rate)(x, deterministic=not train)
        for i in range(self.num_layers):
            x = EncoderBlock(self.num_heads, self.mlp_dim, self.dropout_rate,
                             self.dtype, name="encoderblock_%d" % i)(
                x, deterministic=not train)
        x = nn.LayerNorm(dtype=jnp.float32, name="encoder_norm")(x)
        x = x[:, 0]  # [cls] token
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


ViT_B16 = functools.partial(ViT)  # 86.6M @ 224^2 / 1000 classes
ViT_S16 = functools.partial(ViT, hidden_size=384, num_layers=12, num_heads=6,
                            mlp_dim=1536)  # 22.1M
ViT_L16 = functools.partial(ViT, hidden_size=1024, num_layers=24, num_heads=16,
                            mlp_dim=4096)  # 304M
