"""models subpackage."""
