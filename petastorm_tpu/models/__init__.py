"""Model zoo: acceptance-config models the data plane is measured against (ResNet family for
ImageNet-Parquet, MnistCNN for hello-world, and the SPMD MoE transformer exercising
dp/pp/ep/sp/tp). Lazy imports keep base import light (flax/jax only load on use)."""


def __getattr__(name):
    if name in ("ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101", "ResNet152",
                "BasicBlock", "BottleneckBlock"):
        from petastorm_tpu.models import resnet

        return getattr(resnet, name)
    if name in ("ViT", "ViT_S16", "ViT_B16", "ViT_L16"):
        from petastorm_tpu.models import vit

        return getattr(vit, name)
    if name == "MnistCNN":
        from petastorm_tpu.models.mnist import MnistCNN

        return MnistCNN
    if name in ("TransformerConfig", "init_params", "make_train_step", "param_shardings",
                "model_mesh", "data_sharding", "reference_loss"):
        from petastorm_tpu.models import transformer

        return getattr(transformer, name)
    raise AttributeError("module 'petastorm_tpu.models' has no attribute %r" % name)
