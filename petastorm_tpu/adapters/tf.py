"""TensorFlow adapters: ``make_petastorm_dataset`` and ``tf_tensors``.

Capability parity with petastorm/tf_utils.py (``make_petastorm_dataset`` ~L350,
``tf_tensors`` ~L250, ``_schema_to_tf_dtypes``): a ``tf.data.Dataset`` over a reader with
dtypes/shapes derived from the (post-TransformSpec) Unischema; NGram readers yield
dict-of-namedtuple structures keyed by timestep. Datetime/Decimal fields are converted to
TF-compatible types the way the reference does (dates → int days, datetimes → int64 ns,
Decimal → string).

The reference's per-step ``tf.py_func`` tax is inherent to bridging Python readers into TF;
consumers who care about feed throughput should use the JAX ``DataLoader``. This adapter
exists for migration parity.
"""
from __future__ import annotations

import datetime
import decimal

import numpy as np


def _tf():
    import tensorflow as tf

    return tf


def _field_tf_dtype(tf, field):
    np_dtype = np.dtype(field.numpy_dtype)
    kind = np_dtype.kind
    if kind in "US" or field.numpy_dtype in (str, bytes):
        return tf.string
    if np_dtype == np.dtype("object"):
        return tf.string
    if kind == "M":  # datetime64 -> int64 nanoseconds
        return tf.int64
    return tf.as_dtype(np_dtype)


def _schema_to_tf_dtypes(tf, schema):
    return {name: _field_tf_dtype(tf, f) for name, f in schema.fields.items()}


def _schema_to_tf_shapes(schema):
    out = {}
    for name, f in schema.fields.items():
        if f.shape is None or f.shape == ():
            out[name] = ()
        else:
            out[name] = tuple(d if d is not None else None for d in f.shape)
    return out


def _tf_compatible(value):
    """Convert a decoded python/numpy value to something TF accepts (scalars AND object
    ndarrays of Decimals/dates, which is how batch readers deliver decimal columns)."""
    if isinstance(value, decimal.Decimal):
        return str(value)
    if isinstance(value, datetime.datetime):
        if value.tzinfo is None:
            # naive datetimes are UTC by convention (upstream behavior): timegm reads
            # the struct_time as UTC — value.timestamp() would apply the LOCAL zone
            # and make the same dataset yield different int64s per machine (ADVICE r1)
            import calendar

            epoch_us = calendar.timegm(value.utctimetuple()) * 1_000_000 \
                + value.microsecond
            return np.int64(epoch_us * 1000)
        return np.int64(int(value.timestamp() * 1e9))
    if isinstance(value, datetime.date):
        return np.int64((value - datetime.date(1970, 1, 1)).days)
    if isinstance(value, np.datetime64):
        return value.astype("datetime64[ns]").astype(np.int64)
    if value is None:
        return b""
    if isinstance(value, np.ndarray):
        if value.dtype == object and value.size:
            return np.asarray([_tf_compatible(v) for v in value.reshape(-1)]) \
                .reshape(value.shape)
        if value.dtype.kind == "M":
            return value.astype("datetime64[ns]").astype(np.int64)
    return value


def _reject_device_decode_reader(reader):
    if getattr(reader, "device_decode_fields", None):
        raise ValueError(
            "Reader was built with decode_on_device=True: its image columns carry "
            "device staging payloads only the JAX DataLoader can finish. Use "
            "petastorm_tpu.loader.DataLoader, or rebuild the reader with "
            "decode_on_device=False for the TF path."
        )


def make_petastorm_dataset(reader):
    """``tf.data.Dataset`` over a reader (reference ``make_petastorm_dataset`` ~L350).

    Per-row readers yield dicts of tensors; batch readers yield dicts of batched tensors;
    NGram readers yield ``{timestep: dict}`` structures.
    """
    tf = _tf()
    _reject_device_decode_reader(reader)
    schema = reader.schema

    if reader.ngram is not None:
        if getattr(reader, "is_batched_reader", False):
            raise ValueError(
                "The TF adapter does not support batched NGram readers (their "
                "flat 'offset/field' columns are the JAX DataLoader's device "
                "convention). Use make_reader(schema_fields=ngram) here, or the "
                "JAX DataLoader for the columnar path.")
        return _make_ngram_dataset(tf, reader)

    dtypes = _schema_to_tf_dtypes(tf, schema)
    shapes = _schema_to_tf_shapes(schema)
    if reader.is_batched_reader:
        shapes = {name: (None,) + tuple(s) if s != () else (None,)
                  for name, s in shapes.items()}

    def gen():
        for item in reader:
            d = item._asdict() if hasattr(item, "_asdict") else item
            yield {k: _tf_compatible(v) for k, v in d.items() if k in dtypes}

    signature = {
        name: tf.TensorSpec(shape=shapes[name], dtype=dtypes[name])
        for name in dtypes
    }
    return tf.data.Dataset.from_generator(gen, output_signature=signature)


def _make_ngram_dataset(tf, reader):
    ngram = reader.ngram
    schema = reader.schema
    specs = {}
    for offset in sorted(ngram.fields.keys()):
        names = ngram.get_field_names_at_timestep(offset)
        view = schema.create_schema_view([n for n in names if n in schema.fields])
        specs[str(offset)] = {
            name: tf.TensorSpec(shape=_schema_to_tf_shapes(view)[name],
                                dtype=_schema_to_tf_dtypes(tf, view)[name])
            for name in view.fields
        }

    def gen():
        for window in reader:
            yield {
                str(offset): {k: _tf_compatible(v) for k, v in nt._asdict().items()}
                for offset, nt in window.items()
            }

    return tf.data.Dataset.from_generator(gen, output_signature=specs)


def tf_tensors(reader, shuffling_queue_capacity=0, min_after_dequeue=0):
    """Graph-mode tensors for TF1-style consumers (reference ``tf_tensors`` ~L250).

    Returns a structure of tensors that advances the reader each time it is evaluated.
    In TF2 eager this delegates to a dataset iterator.

    ``min_after_dequeue`` maps onto tf.data semantics as a floor on the shuffle buffer
    (the reference's ``tf.train.shuffle_batch`` used it as the minimum buffered rows
    for shuffle quality): the effective buffer is
    ``max(shuffling_queue_capacity, min_after_dequeue + 1)``.
    """
    tf = _tf()
    _reject_device_decode_reader(reader)
    buffer_size = max(int(shuffling_queue_capacity or 0), int(min_after_dequeue or 0) + 1
                      if min_after_dequeue else 0)
    if buffer_size > 1:
        ds = make_petastorm_dataset(reader).shuffle(
            buffer_size, seed=None, reshuffle_each_iteration=True)
    else:
        ds = make_petastorm_dataset(reader)
    if tf.executing_eagerly():
        it = iter(ds)
        return lambda: next(it)
    it = tf.compat.v1.data.make_one_shot_iterator(ds)
    return it.get_next()
