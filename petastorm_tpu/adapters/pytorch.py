"""PyTorch adapters: ``DataLoader``, ``BatchedDataLoader``, ``InMemBatchedDataLoader``.

Capability parity with petastorm/pytorch.py (``decimal_friendly_collate`` ~L40, ``LoaderBase``
~L80, ``DataLoader`` ~L120, ``BatchedDataLoader`` ~L260, ``InMemBatchedDataLoader`` ~L380):
torch-facing loaders over our readers, with host-side shuffling buffers. The vectorized
``BatchedDataLoader`` rides the same columnar path the JAX loader uses (numpy column dicts →
``torch.as_tensor`` zero-copy) instead of per-row collate.
"""
from __future__ import annotations

import decimal
import logging

import numpy as np

from petastorm_tpu.shuffle import NoopShufflingBuffer, RandomShufflingBuffer

logger = logging.getLogger(__name__)


def decimal_friendly_collate(batch):
    """default_collate that passes ``decimal.Decimal`` (and other unconvertibles) through as
    lists (reference ``decimal_friendly_collate`` petastorm/pytorch.py ~L40)."""
    import torch

    first = batch[0]
    if isinstance(first, decimal.Decimal):
        return list(batch)
    if isinstance(first, (dict,)):
        return {k: decimal_friendly_collate([d[k] for d in batch]) for k in first}
    if hasattr(first, "_fields"):  # namedtuple
        return type(first)(*(decimal_friendly_collate([getattr(d, f) for d in batch])
                             for f in first._fields))
    if isinstance(first, (list, tuple)):
        return [decimal_friendly_collate(list(s)) for s in zip(*batch)]
    try:
        return torch.utils.data.default_collate(batch)
    except TypeError:
        return list(batch)


class LoaderBase:
    """Iterator + shutdown plumbing shared by the torch loaders (reference ~L80)."""

    def __init__(self, reader):
        if getattr(reader, "device_decode_fields", None):
            raise ValueError(
                "Reader was built with decode_on_device=True: its image columns carry "
                "device staging payloads only the JAX DataLoader can finish. Use "
                "petastorm_tpu.loader.DataLoader, or rebuild the reader with "
                "decode_on_device=False for the torch path."
            )
        if getattr(reader, "ngram", None) is not None \
                and getattr(reader, "is_batched_reader", False):
            raise ValueError(
                "The torch adapters do not support batched NGram readers (their "
                "flat 'offset/field' columns are the JAX DataLoader's device "
                "convention). Use make_reader(schema_fields=ngram) here, or the "
                "JAX DataLoader for the columnar path.")
        self.reader = reader
        self._stopped = False

    def __iter__(self):
        try:
            yield from self._iter_impl()
        except Exception:
            self.stop()
            raise

    def stop(self):
        self._stopped = True
        self.reader.stop()

    def join(self):
        self.reader.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        self.join()


class DataLoader(LoaderBase):
    """Per-row loader: reader rows → shuffling queue → ``collate_fn`` batches (reference
    ``DataLoader`` ~L120). Use with ``make_reader``; for ``make_batch_reader`` prefer
    :class:`BatchedDataLoader`."""

    def __init__(self, reader, batch_size=1, collate_fn=decimal_friendly_collate,
                 shuffling_queue_capacity=0, seed=None):
        super().__init__(reader)
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self.shuffling_queue_capacity = shuffling_queue_capacity
        self._seed = seed

    def _make_buffer(self):
        if self.shuffling_queue_capacity > 0:
            min_after = max(1, self.shuffling_queue_capacity // 2)
            return RandomShufflingBuffer(self.shuffling_queue_capacity, min_after,
                                         seed=self._seed)
        return NoopShufflingBuffer()

    def _iter_impl(self):
        buffer = self._make_buffer()
        rows = []
        for row in self.reader:
            if self._stopped:
                return
            buffer.add_many([row._asdict() if hasattr(row, "_asdict") else row])
            while buffer.can_retrieve:
                rows.append(buffer.retrieve())
                if len(rows) == self.batch_size:
                    yield self.collate_fn(rows)
                    rows = []
        buffer.finish()
        while buffer.can_retrieve:
            rows.append(buffer.retrieve())
            if len(rows) == self.batch_size:
                yield self.collate_fn(rows)
                rows = []
        if rows:
            yield self.collate_fn(rows)


class BatchedDataLoader(LoaderBase):
    """Vectorized loader over the columnar batch path (reference ``BatchedDataLoader``
    ~L260): numpy column dicts → batched shuffle buffer → torch tensors, no per-row work.

    Non-tensorizable columns (strings, objects, decimals) are yielded as numpy arrays.
    """

    def __init__(self, reader, batch_size=1, shuffling_queue_capacity=0, seed=None,
                 keep_last_batch=True):
        super().__init__(reader)
        self.batch_size = batch_size
        self.shuffling_queue_capacity = shuffling_queue_capacity
        self._seed = seed
        self.keep_last_batch = keep_last_batch

    def _iter_impl(self):
        import torch

        from petastorm_tpu.loader import _HostBatcher

        batcher = _HostBatcher(self.batch_size, self.shuffling_queue_capacity, self._seed)

        def to_torch(batch):
            return {k: self._to_torch(torch, v) for k, v in batch.items()}

        for item in self.reader:
            if self._stopped:
                return
            columns = item._asdict() if hasattr(item, "_asdict") else item
            columns = {k: v for k, v in columns.items() if v is not None}
            if columns:
                for batch in batcher.add(columns):
                    yield to_torch(batch)
        for batch in batcher.finish():
            n = len(next(iter(batch.values()))) if batch else 0
            if n == self.batch_size or (n and self.keep_last_batch):
                yield to_torch(batch)

    @staticmethod
    def _to_torch(torch, arr):
        if isinstance(arr, np.ndarray) and arr.dtype.kind in "biufc":
            return torch.as_tensor(arr)
        return arr


class InMemBatchedDataLoader(LoaderBase):
    """Loads up to ``rows_capacity`` rows ONCE, then serves epochs from memory with
    per-epoch reshuffling (reference ``InMemBatchedDataLoader`` ~L380)."""

    def __init__(self, reader, batch_size=1, num_epochs=1, rows_capacity=None,
                 shuffle=True, seed=None):
        super().__init__(reader)
        self.batch_size = batch_size
        self.num_epochs = num_epochs
        self.rows_capacity = rows_capacity
        self.shuffle = shuffle
        self._rng = np.random.Generator(np.random.PCG64(seed))
        self._columns = None

    def _load(self):
        chunks = {}
        total = 0
        for item in self.reader:
            columns = item._asdict() if hasattr(item, "_asdict") else item
            columns = {k: v for k, v in columns.items() if v is not None}
            if not columns:
                continue
            if not all(isinstance(v, np.ndarray) and v.ndim >= 1 for v in columns.values()):
                from petastorm_tpu.loader import _rows_to_columns

                columns = _rows_to_columns([columns])
            n = len(next(iter(columns.values())))
            for k, v in columns.items():
                chunks.setdefault(k, []).append(v)
            total += n
            if self.rows_capacity is not None and total >= self.rows_capacity:
                break
        if not chunks:
            raise ValueError("reader produced no rows to preload")
        cols = {k: np.concatenate(v, axis=0) if v[0].dtype != object
                else _object_concat(v) for k, v in chunks.items()}
        if self.rows_capacity is not None:
            cols = {k: v[: self.rows_capacity] for k, v in cols.items()}
        self._columns = cols

    def _iter_impl(self):
        import torch

        if self._columns is None:
            self._load()
        n = len(next(iter(self._columns.values())))
        for _ in range(self.num_epochs):
            order = self._rng.permutation(n) if self.shuffle else np.arange(n)
            for start in range(0, n, self.batch_size):
                if self._stopped:
                    return
                idx = order[start: start + self.batch_size]
                yield {k: BatchedDataLoader._to_torch(torch, v[idx])
                       for k, v in self._columns.items()}


def _object_concat(chunks):
    total = sum(len(c) for c in chunks)
    out = np.empty(total, dtype=object)
    pos = 0
    for c in chunks:
        out[pos: pos + len(c)] = c
        pos += len(c)
    return out
