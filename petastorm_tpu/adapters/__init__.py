"""adapters subpackage."""
