"""Framework adapters: PyTorch loaders and TensorFlow dataset bridges (reference
petastorm/pytorch.py, petastorm/tf_utils.py). Import lazily — torch/tf are optional."""


def __getattr__(name):
    if name in ("DataLoader", "BatchedDataLoader", "InMemBatchedDataLoader",
                "decimal_friendly_collate"):
        from petastorm_tpu.adapters import pytorch

        return getattr(pytorch, name)
    if name in ("make_petastorm_dataset", "tf_tensors"):
        from petastorm_tpu.adapters import tf

        return getattr(tf, name)
    raise AttributeError("module 'petastorm_tpu.adapters' has no attribute %r" % name)
