"""Read datasets written by real petastorm without importing petastorm.

Reference datasets persist a *pickled* ``Unischema`` under the Parquet KV key
``dataset-toolkit.unischema.v1`` (petastorm/etl/dataset_metadata.py ~L60 ``UNISCHEMA_KEY``; the
pre-rename key handled by petastorm/etl/legacy.py is also accepted). The pickle stream names
``petastorm.unischema`` / ``petastorm.codecs`` / ``pyspark.sql.types`` classes; this unpickler
maps those module paths onto our equivalents so the bytes deserialize into *our* objects —
no petastorm, no pyspark required.
"""
from __future__ import annotations

import io
import pickle

_CLASS_MAP = {
    # petastorm core → ours (same attribute names by design; __setstate__ shims cover deltas)
    ("petastorm.unischema", "Unischema"): ("petastorm_tpu.unischema", "Unischema"),
    ("petastorm.unischema", "UnischemaField"): ("petastorm_tpu.unischema", "UnischemaField"),
    ("petastorm.codecs", "ScalarCodec"): ("petastorm_tpu.codecs", "ScalarCodec"),
    ("petastorm.codecs", "NdarrayCodec"): ("petastorm_tpu.codecs", "NdarrayCodec"),
    ("petastorm.codecs", "CompressedNdarrayCodec"): (
        "petastorm_tpu.codecs",
        "CompressedNdarrayCodec",
    ),
    ("petastorm.codecs", "CompressedImageCodec"): (
        "petastorm_tpu.codecs",
        "CompressedImageCodec",
    ),
    # legacy pre-rename package (petastorm/etl/legacy.py ~L20)
    ("dataset_toolkit.unischema", "Unischema"): ("petastorm_tpu.unischema", "Unischema"),
    ("dataset_toolkit.unischema", "UnischemaField"): ("petastorm_tpu.unischema", "UnischemaField"),
    ("dataset_toolkit.codecs", "ScalarCodec"): ("petastorm_tpu.codecs", "ScalarCodec"),
    ("dataset_toolkit.codecs", "NdarrayCodec"): ("petastorm_tpu.codecs", "NdarrayCodec"),
    ("dataset_toolkit.codecs", "CompressedNdarrayCodec"): (
        "petastorm_tpu.codecs",
        "CompressedNdarrayCodec",
    ),
    ("dataset_toolkit.codecs", "CompressedImageCodec"): (
        "petastorm_tpu.codecs",
        "CompressedImageCodec",
    ),
}

_PYSPARK_TYPE_NAMES = {
    "BooleanType", "ByteType", "ShortType", "IntegerType", "LongType", "FloatType",
    "DoubleType", "StringType", "BinaryType", "DateType", "TimestampType", "DecimalType",
}


class _ReferenceUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if (module, name) in _CLASS_MAP:
            target_module, target_name = _CLASS_MAP[(module, name)]
            mod = __import__(target_module, fromlist=[target_name])
            return getattr(mod, target_name)
        if module.startswith("pyspark.sql.types") and name in _PYSPARK_TYPE_NAMES:
            from petastorm_tpu import types as ptypes

            return getattr(ptypes, name)
        if module.startswith(("petastorm", "dataset_toolkit", "pyspark")):
            raise pickle.UnpicklingError(
                "Reference pickle references unsupported class %s.%s" % (module, name)
            )
        return super().find_class(module, name)


def loads_reference_pickle(payload):
    """Deserialize a reference-petastorm pickle into petastorm_tpu objects."""
    return _ReferenceUnpickler(io.BytesIO(payload)).load()
