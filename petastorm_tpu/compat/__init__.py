"""compat subpackage."""
