"""compat subpackage."""


def shard_map():
    """The ``shard_map`` entry point across jax versions (ISSUE 12 satellite):
    new jax exposes ``jax.shard_map`` at top level; 0.4.x only ships
    ``jax.experimental.shard_map.shard_map``. Returns the callable.

    On the experimental (0.4.x) path the static replication check is
    disabled: its inference cannot see the ``psum`` inside a
    ``value_and_grad`` of a collective loss and rejects replicated
    out_specs that ARE replicated at runtime (the oracle tests pin the
    numbers either way); new jax's varying-axes types made the check
    precise, so it stays on there."""
    import functools

    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    from jax.experimental.shard_map import shard_map as exp_shard_map

    return functools.partial(exp_shard_map, check_rep=False)
