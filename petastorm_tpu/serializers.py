"""Wire serializers for the process pool (reference parity:
petastorm/reader_impl/pickle_serializer.py ``PickleSerializer`` and
petastorm/reader_impl/arrow_table_serializer.py ``ArrowTableSerializer`` ~L20, which
rode ZeroMQ multipart for zero-copy).

Here the wire is a ``multiprocessing.connection`` unix socket; all serializers speak
the same frame protocol — ``serialize(obj) -> (kind, [buffer, ...])`` and
``deserialize(kind, [buffer, ...]) -> obj`` — so the pool can ship each buffer with
``send_bytes`` and avoid the single monolithic pickle stream:

- :class:`PickleSerializer` uses pickle protocol 5 with out-of-band buffers: numpy
  array payloads are extracted as raw PickleBuffer views and written to the socket
  directly instead of being copied into the pickle stream first.
- :class:`ArrowTableSerializer` recognizes the tagged columnar results the batch path
  produces — ``(epoch, ordinal, {name: ndarray})`` — and encodes the numeric columns
  as one Arrow IPC stream (tensor columns flatten to FixedSizeList with the shape in
  field metadata); payloads it cannot express fall back to pickle frames (the ``kind``
  byte disambiguates on the receiving end).
- :class:`ShmSerializer` composes with EITHER framing above: the frames the inner
  serializer produces are written by the child directly into a granted shared-memory
  slab (:mod:`petastorm_tpu.parallel.shm_ring`) and only a small descriptor crosses
  the socket; the parent reconstructs buffer views into the slab — no socket copy,
  no recv allocation. Oversized payloads (or items with no slab grant) fall back to
  the inner serializer's socket frames transparently: ``deserialize`` dispatches on
  the ``kind`` byte either way.

Writable-batch contract: deserialized payloads must match the thread pool's
contract — arrays a consumer may mutate in place. The default (``writable=True``)
copies exactly the read-only reconstructions (one payload copy, the same count the
old socket wire paid AFTER its recv copy). ``writable=False`` ("view mode",
serializer names ending in ``-view``) skips that copy and delivers READ-ONLY
zero-copy views into the slab plus a :class:`petastorm_tpu.io.lease.Lease`
riding with the batch; a consumer that mutates gets an immediate
``ValueError: assignment destination is read-only`` (fail-loud, never corruption),
and the slab returns to the ring when the lease is released —
``Reader.release_batch()``, batch drop (refcount), or pool ``join()``.
"""
from __future__ import annotations

import pickle

import numpy as np

from petastorm_tpu.io.lease import LEASE_KEY, Lease, count_copy
from petastorm_tpu.obs.log import degradation

KIND_PICKLE = 0
KIND_ARROW = 1
KIND_SHM = 2

#: reserved key under which a view-mode batch's lease rides inside the tagged
#: columnar payload dict — the Reader pops it before exposing the batch. Since
#: ISSUE 6 this is the GENERIC :class:`petastorm_tpu.io.lease.Lease` key (the
#: slab ring is one backend of the contract, not a special case); the old name
#: is kept as an alias for existing imports.
SHM_LEASE_KEY = LEASE_KEY

#: frame offsets inside a slab are rounded up to this (cache-line / SIMD-friendly
#: reconstruction of ndarray views)
_SLAB_ALIGN = 64


def _ensure_writable(obj):
    """Deserialized payloads must match the thread pool's contract: WRITABLE arrays.

    Out-of-band pickle-5 buffers and zero-copy Arrow views reconstruct as read-only
    ndarrays; a consumer mutating batches in place (``batch['image'] /= 255``) must not
    break depending on pool type. Copies only when actually read-only — the same copy
    count as the old monolithic-pickle wire, still saving its stream-assembly copy.
    Every byte copied here is charged to the ``wire_writable`` copy-census site
    (the `-view` wires exist to make this number zero)."""
    copied = [0]
    out = _ensure_writable_impl(obj, copied)
    count_copy("wire_writable", copied[0])
    return out


def _ensure_writable_impl(obj, copied):
    if isinstance(obj, np.ndarray):
        if obj.dtype.hasobject or obj.flags.writeable:
            return obj
        copied[0] += obj.nbytes
        return obj.copy()
    if isinstance(obj, dict):
        return {k: _ensure_writable_impl(v, copied) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_ensure_writable_impl(v, copied) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_ensure_writable_impl(v, copied) for v in obj)
    return obj


class PickleSerializer:
    """Pickle protocol 5 with out-of-band buffers (no intermediate stream copy).

    ``ensure_writable=False`` (the shm view mode) skips the read-only→writable
    copy and hands back zero-copy reconstructions as-is."""

    def __init__(self, ensure_writable=True):
        self._ensure = ensure_writable

    def serialize(self, obj):
        buffers = []
        head = pickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
        return KIND_PICKLE, [head] + [b.raw() for b in buffers]

    def deserialize(self, kind, frames):
        if kind != KIND_PICKLE:
            raise ValueError("PickleSerializer got kind %r" % kind)
        obj = pickle.loads(frames[0], buffers=frames[1:])
        return _ensure_writable(obj) if self._ensure else obj


def _arrow_expressible(columns):
    for arr in columns.values():
        if not isinstance(arr, np.ndarray) or arr.dtype.hasobject:
            return False
        if arr.dtype.kind not in "biufc" and arr.dtype.kind not in ("U", "S"):
            return False
    return True


class ArrowTableSerializer(PickleSerializer):
    """Arrow IPC for tagged columnar batch results; pickle fallback otherwise."""

    def serialize(self, obj):
        if (
            isinstance(obj, tuple) and len(obj) == 3
            and isinstance(obj[2], dict) and obj[2]
            and _arrow_expressible(obj[2])
        ):
            try:
                return KIND_ARROW, [self._encode(obj)]
            except Exception as e:  # noqa: BLE001 - arrow can't express it:
                # pickle instead — but COUNT it (ISSUE 5 GL-O002): a wire that
                # silently downgrades per batch hides a real perf cliff
                from petastorm_tpu.obs.log import degradation

                degradation(
                    "arrow_fallback",
                    "Arrow IPC encode failed (%s); this batch rides the pickle "
                    "wire", e)
        return super().serialize(obj)

    def deserialize(self, kind, frames):
        if kind == KIND_ARROW:
            return self._decode(frames[0], ensure_writable=self._ensure)
        return super().deserialize(kind, frames)

    @staticmethod
    def _encode(obj):
        import pyarrow as pa

        epoch, ordinal, columns = obj
        fields = []
        arrays = []
        for name, arr in columns.items():
            if arr.dtype.kind in ("U", "S"):
                # dtype kind rides in metadata so decode restores the exact numpy kind
                # ('S' bytes must NOT come back as str — pa.binary vs pa.string)
                pa_type = pa.string() if arr.dtype.kind == "U" else pa.binary()
                pa_arr = pa.array(arr.tolist(), type=pa_type)
                fields.append(pa.field(name, pa_arr.type,
                                       metadata={b"npkind": arr.dtype.kind.encode()}))
            elif arr.ndim == 1:
                pa_arr = pa.array(arr)
                fields.append(pa.field(name, pa_arr.type))
            else:
                flat_len = int(np.prod(arr.shape[1:]))
                flat = np.ascontiguousarray(arr).reshape(len(arr) * flat_len)
                pa_arr = pa.FixedSizeListArray.from_arrays(pa.array(flat), flat_len)
                import json

                fields.append(pa.field(
                    name, pa_arr.type,
                    metadata={b"shape": json.dumps(list(arr.shape[1:])).encode()},
                ))
            arrays.append(pa_arr)
        schema = pa.schema(fields, metadata={
            b"epoch": str(epoch).encode(), b"ordinal": str(ordinal).encode(),
        })
        batch = pa.record_batch(arrays, schema=schema)
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, schema) as writer:
            writer.write_batch(batch)
        return sink.getvalue()

    @staticmethod
    def _decode(frame, ensure_writable=True):
        import pyarrow as pa

        with pa.ipc.open_stream(pa.py_buffer(frame)) as reader:
            batch = reader.read_next_batch()
            schema = reader.schema
        epoch = int(schema.metadata[b"epoch"])
        ordinal = int(schema.metadata[b"ordinal"])
        columns = {}
        for i, field in enumerate(schema):
            col = batch.column(i)
            meta = field.metadata or {}
            if b"shape" in meta:
                import json

                inner = json.loads(meta[b"shape"].decode())
                flat = col.flatten().to_numpy(zero_copy_only=False)
                columns[field.name] = flat.reshape((len(col),) + tuple(inner))
            elif b"npkind" in meta:
                kind = meta[b"npkind"].decode()
                columns[field.name] = np.asarray(
                    col.to_pylist(), dtype=np.str_ if kind == "U" else np.bytes_)
            else:
                columns[field.name] = col.to_numpy(zero_copy_only=False)
        if ensure_writable:
            columns = _ensure_writable(columns)
        return epoch, ordinal, columns


class _LeasedRows(list):
    """Per-row payload list that carries its lease (view mode); the Reader
    holds the lease while it drains the buffered rows."""

    lease = None


class ShmSerializer:
    """Slab transport composing an inner framing (pickle or Arrow).

    Child side (``bind_slabs`` + per-item ``set_slab``): writes the inner
    serializer's frames into the granted slab and ships a descriptor —
    ``(inner_kind, slab_id, [(offset, length), ...])`` — as the only socket frame.
    Items without a grant, or whose frames exceed the slab size, ship the inner
    frames over the socket unchanged (the ``kind`` disambiguates).

    Parent side (``bind_ring``): reconstructs the inner frames as zero-copy
    memoryviews into the slab. With ``writable=True`` (default) the inner
    deserializer's writable-batch copy runs and the slab is released immediately;
    with ``writable=False`` read-only views are delivered with a refcounted
    :class:`petastorm_tpu.io.lease.Lease` (backed by the ring's
    :class:`~petastorm_tpu.parallel.shm_ring.SlabLease`) attached to the
    payload.
    """

    def __init__(self, inner_name="pickle", writable=True):
        if inner_name not in ("pickle", "arrow"):
            raise ValueError("ShmSerializer inner must be 'pickle' or 'arrow', "
                             "got %r" % inner_name)
        self.inner_name = inner_name
        self.writable = writable
        inner_cls = PickleSerializer if inner_name == "pickle" else ArrowTableSerializer
        self.inner = inner_cls(ensure_writable=writable)
        self._client = None   # child side: SlabClient
        self._slab = None     # child side: per-item grant
        self._ring = None     # parent side: SlabRing

    # -- child side ---------------------------------------------------------------------

    def bind_slabs(self, names, slab_bytes):
        from petastorm_tpu.parallel.shm_ring import SlabClient

        self._client = SlabClient(names, slab_bytes)

    def set_slab(self, slab_id):
        """Install the parent's grant for the NEXT serialize() call (None = the
        parent could not acquire a slab; serialize falls back to socket frames)."""
        self._slab = slab_id

    def close(self):
        if self._client is not None:
            self._client.close()

    def serialize(self, obj):
        kind, frames = self.inner.serialize(obj)
        slab, self._slab = self._slab, None
        if slab is None or self._client is None:
            return kind, frames
        views = [memoryview(f).cast("B") for f in frames]
        end = 0
        offsets = []
        for v in views:
            start = -(-end // _SLAB_ALIGN) * _SLAB_ALIGN  # round up
            end = start + v.nbytes
            offsets.append((start, v.nbytes))
        if end > self._client.slab_bytes:
            # oversized payload: socket fallback for this item; the parent sees a
            # non-shm kind and returns the unused slab to the ring
            return kind, frames
        buf = self._client.buffer(slab)
        for v, (start, length) in zip(views, offsets):
            buf[start:start + length] = v
        # the descriptor carries a crc trailer: a corrupted descriptor must be
        # DETECTED, never acted on — a byte flip could otherwise still parse
        # into a valid pickle naming a DIFFERENT slab id, and releasing that
        # id would free a slab some other consumer's lease still views
        blob = pickle.dumps((kind, slab, offsets))
        import struct
        import zlib

        return KIND_SHM, [blob + struct.pack("<I", zlib.crc32(blob))]

    # -- parent side --------------------------------------------------------------------

    def bind_ring(self, ring):
        self._ring = ring

    def deserialize(self, kind, frames):
        if kind != KIND_SHM:
            return self.inner.deserialize(kind, frames)
        if self._ring is None:
            raise ValueError("shm descriptor received but no slab ring is bound")
        # Slab-ownership contract with the caller (the pool driver): exceptions
        # raised BEFORE this method takes ownership of the granted slab carry
        # ``slab_released = False`` — the caller still owns the grant and must
        # return it; exceptions raised AFTER carry ``slab_released = True``
        # (the lease's failure handler below already returned it). Without the
        # marker a decode failure either leaked the slab or double-released it.
        try:
            import struct
            import zlib

            desc = memoryview(frames[0]).cast("B")
            if len(desc) < 5:
                raise ValueError("shm descriptor truncated (%d bytes)"
                                 % len(desc))
            blob, (crc,) = desc[:-4], struct.unpack("<I", desc[-4:])
            if zlib.crc32(blob) != crc:
                raise ValueError(
                    "shm descriptor failed its crc check (corrupt wire bytes)")
            inner_kind, slab, offsets = pickle.loads(blob)
        except Exception as e:
            e.slab_released = False
            raise
        from petastorm_tpu.parallel.shm_ring import SlabLease

        # view mode speaks the generic Lease contract over the slab backend:
        # the ring's own SlabLease keeps the exactly-once free-list insert, the
        # Lease adds refcounting (retain per holder), revocation, and the
        # ptpu_lease_* accounting the loader's retention path builds on. The
        # writable path releases before returning, so it skips the wrapper.
        slab_lease = SlabLease(self._ring, slab)
        if self.writable:
            lease = slab_lease
        else:
            lease = Lease(release_cb=slab_lease.release, kind="shm_slab")
            # lease-aware reclaim (ISSUE 7): the ring must know a consumer may
            # retain views over this slab, so a dead-child reclaim REVOKES the
            # lease instead of re-granting a still-viewed slab
            register = getattr(self._ring, "register_lease", None)
            if register is not None:
                register(slab, lease)
        try:
            base = self._ring.buffer(slab)
            self._ring.add_bytes(sum(length for _s, length in offsets))
            if self.writable and inner_kind == KIND_PICKLE:
                result = self._deserialize_owned(base, inner_kind, offsets)
            else:
                # arrow framing reconstructs only flat numeric/string columns
                # (object payloads never ride it), all visible to the writable
                # walk — zero-copy views are safe; view mode wants views anyway
                views = [base[start:start + length].toreadonly()
                         for start, length in offsets]
                result = self.inner.deserialize(inner_kind, views)
                del views
            if not self.writable:
                attached = self._attach_lease(result, lease)
                if attached is not None:
                    return attached
                # unrecognized result shape (ad-hoc worker return): the lease has
                # nowhere to ride, so views into the slab would go stale at the
                # release below. Rebuild the payload from OWNED buffers — the
                # writable-path treatment — then release; correctness never
                # depends on the consumer knowing about leases.
                degradation(
                    "shm_view_copyout",
                    "shm view-mode payload of type %s cannot carry a slab "
                    "lease; delivering an owned copy instead of zero-copy "
                    "views", type(result).__name__)
                if inner_kind == KIND_PICKLE:
                    result = self._deserialize_owned(base, inner_kind, offsets)
                else:
                    result = _ensure_writable(result)
        except BaseException as e:
            lease.release()
            e.slab_released = True
            raise
        # every slab reference was either copied by the inner deserializer
        # (arrow) or backed by owned buffers (pickle) — return the slab now
        lease.release()
        return result

    def _deserialize_owned(self, base, inner_kind, offsets):
        """Inner deserialize with the out-of-band buffers backed by OWNED writable
        copies instead of slab views: pickle-5 reattaches buffers ANYWHERE in the
        object graph — object-array ELEMENTS (ragged columns), custom staging
        payloads — where the writable-contract walk cannot reach them, so slab
        views there would go stale at release and corrupt silently on slab reuse.
        Reconstructions come out writable (_ensure_writable then no-ops), and
        this is the one payload copy the safe modes budget either way."""
        head_start, head_len = offsets[0]
        frames = [base[head_start:head_start + head_len].toreadonly()]
        frames += [bytearray(base[start:start + length])
                   for start, length in offsets[1:]]
        count_copy("wire_owned", sum(length for _s, length in offsets[1:]))
        return self.inner.deserialize(inner_kind, frames)

    @staticmethod
    def _attach_lease(result, lease):
        """Ride the lease with the payload the decode path produces; None when the
        result shape is unrecognized (caller then copies out and releases)."""
        if isinstance(result, tuple) and len(result) == 3:
            epoch, ordinal, payload = result
            if isinstance(payload, dict):
                payload[SHM_LEASE_KEY] = lease
                return result
            if isinstance(payload, list):
                leased = _LeasedRows(payload)
                leased.lease = lease
                return (epoch, ordinal, leased)
        return None


#: serializer name → (constructor kwargs) for the shm family; the name string is
#: what crosses the bootstrap handshake, so both ends agree from it alone
_SHM_NAMES = {
    "shm": dict(inner_name="pickle", writable=True),
    "shm-pickle": dict(inner_name="pickle", writable=True),
    "shm-arrow": dict(inner_name="arrow", writable=True),
    "shm-view": dict(inner_name="pickle", writable=False),
    "shm-pickle-view": dict(inner_name="pickle", writable=False),
    "shm-arrow-view": dict(inner_name="arrow", writable=False),
}


def make_serializer(name):
    if name in (None, "pickle"):
        return PickleSerializer()
    if name == "arrow":
        return ArrowTableSerializer()
    if name in _SHM_NAMES:
        return ShmSerializer(**_SHM_NAMES[name])
    raise ValueError(
        "Unknown serializer %r (expected 'pickle', 'arrow', or one of %s)"
        % (name, sorted(_SHM_NAMES)))
