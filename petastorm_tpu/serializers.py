"""Wire serializers for the process pool (reference parity:
petastorm/reader_impl/pickle_serializer.py ``PickleSerializer`` and
petastorm/reader_impl/arrow_table_serializer.py ``ArrowTableSerializer`` ~L20, which
rode ZeroMQ multipart for zero-copy).

Here the wire is a ``multiprocessing.connection`` unix socket; both serializers speak
the same frame protocol — ``serialize(obj) -> (kind, [buffer, ...])`` and
``deserialize(kind, [buffer, ...]) -> obj`` — so the pool can ship each buffer with
``send_bytes`` and avoid the single monolithic pickle stream:

- :class:`PickleSerializer` uses pickle protocol 5 with out-of-band buffers: numpy
  array payloads are extracted as raw PickleBuffer views and written to the socket
  directly instead of being copied into the pickle stream first.
- :class:`ArrowTableSerializer` recognizes the tagged columnar results the batch path
  produces — ``(epoch, ordinal, {name: ndarray})`` — and encodes the numeric columns
  as one Arrow IPC stream (tensor columns flatten to FixedSizeList with the shape in
  field metadata); payloads it cannot express fall back to pickle frames (the ``kind``
  byte disambiguates on the receiving end).
"""
from __future__ import annotations

import pickle

import numpy as np

KIND_PICKLE = 0
KIND_ARROW = 1


def _ensure_writable(obj):
    """Deserialized payloads must match the thread pool's contract: WRITABLE arrays.

    Out-of-band pickle-5 buffers and zero-copy Arrow views reconstruct as read-only
    ndarrays; a consumer mutating batches in place (``batch['image'] /= 255``) must not
    break depending on pool type. Copies only when actually read-only — the same copy
    count as the old monolithic-pickle wire, still saving its stream-assembly copy."""
    if isinstance(obj, np.ndarray):
        return obj if obj.dtype.hasobject or obj.flags.writeable else obj.copy()
    if isinstance(obj, dict):
        return {k: _ensure_writable(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_ensure_writable(v) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_ensure_writable(v) for v in obj)
    return obj


class PickleSerializer:
    """Pickle protocol 5 with out-of-band buffers (no intermediate stream copy)."""

    def serialize(self, obj):
        buffers = []
        head = pickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
        return KIND_PICKLE, [head] + [b.raw() for b in buffers]

    def deserialize(self, kind, frames):
        if kind != KIND_PICKLE:
            raise ValueError("PickleSerializer got kind %r" % kind)
        return _ensure_writable(pickle.loads(frames[0], buffers=frames[1:]))


def _arrow_expressible(columns):
    for arr in columns.values():
        if not isinstance(arr, np.ndarray) or arr.dtype.hasobject:
            return False
        if arr.dtype.kind not in "biufc" and arr.dtype.kind not in ("U", "S"):
            return False
    return True


class ArrowTableSerializer(PickleSerializer):
    """Arrow IPC for tagged columnar batch results; pickle fallback otherwise."""

    def serialize(self, obj):
        if (
            isinstance(obj, tuple) and len(obj) == 3
            and isinstance(obj[2], dict) and obj[2]
            and _arrow_expressible(obj[2])
        ):
            try:
                return KIND_ARROW, [self._encode(obj)]
            except Exception:  # noqa: BLE001 - arrow can't express it: pickle instead
                pass
        return super().serialize(obj)

    def deserialize(self, kind, frames):
        if kind == KIND_ARROW:
            return self._decode(frames[0])
        return super().deserialize(kind, frames)

    @staticmethod
    def _encode(obj):
        import pyarrow as pa

        epoch, ordinal, columns = obj
        fields = []
        arrays = []
        for name, arr in columns.items():
            if arr.dtype.kind in ("U", "S"):
                # dtype kind rides in metadata so decode restores the exact numpy kind
                # ('S' bytes must NOT come back as str — pa.binary vs pa.string)
                pa_type = pa.string() if arr.dtype.kind == "U" else pa.binary()
                pa_arr = pa.array(arr.tolist(), type=pa_type)
                fields.append(pa.field(name, pa_arr.type,
                                       metadata={b"npkind": arr.dtype.kind.encode()}))
            elif arr.ndim == 1:
                pa_arr = pa.array(arr)
                fields.append(pa.field(name, pa_arr.type))
            else:
                flat_len = int(np.prod(arr.shape[1:]))
                flat = np.ascontiguousarray(arr).reshape(len(arr) * flat_len)
                pa_arr = pa.FixedSizeListArray.from_arrays(pa.array(flat), flat_len)
                import json

                fields.append(pa.field(
                    name, pa_arr.type,
                    metadata={b"shape": json.dumps(list(arr.shape[1:])).encode()},
                ))
            arrays.append(pa_arr)
        schema = pa.schema(fields, metadata={
            b"epoch": str(epoch).encode(), b"ordinal": str(ordinal).encode(),
        })
        batch = pa.record_batch(arrays, schema=schema)
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, schema) as writer:
            writer.write_batch(batch)
        return sink.getvalue()

    @staticmethod
    def _decode(frame):
        import pyarrow as pa

        with pa.ipc.open_stream(pa.py_buffer(frame)) as reader:
            batch = reader.read_next_batch()
            schema = reader.schema
        epoch = int(schema.metadata[b"epoch"])
        ordinal = int(schema.metadata[b"ordinal"])
        columns = {}
        for i, field in enumerate(schema):
            col = batch.column(i)
            meta = field.metadata or {}
            if b"shape" in meta:
                import json

                inner = json.loads(meta[b"shape"].decode())
                flat = col.flatten().to_numpy(zero_copy_only=False)
                columns[field.name] = flat.reshape((len(col),) + tuple(inner))
            elif b"npkind" in meta:
                kind = meta[b"npkind"].decode()
                columns[field.name] = np.asarray(
                    col.to_pylist(), dtype=np.str_ if kind == "U" else np.bytes_)
            else:
                columns[field.name] = col.to_numpy(zero_copy_only=False)
        return epoch, ordinal, _ensure_writable(columns)


def make_serializer(name):
    if name in (None, "pickle"):
        return PickleSerializer()
    if name == "arrow":
        return ArrowTableSerializer()
    raise ValueError("Unknown serializer %r (expected 'pickle' or 'arrow')" % name)
