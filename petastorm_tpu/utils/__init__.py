"""Row decode driver and small helpers (reference: petastorm/utils.py ~L80 ``decode_row``)."""
from __future__ import annotations

import numpy as np

from petastorm_tpu.errors import DecodeFieldError


def decode_row(row, schema, device_fields=(), prestaged=None):
    """Decode one stored row dict through codecs into a {field: numpy value} dict.

    Mirrors the reference decode driver (petastorm/utils.py ~L80): codec dispatch plus nullable
    handling; wraps codec failures with the field name for debuggability.

    Fields named in ``device_fields`` run only the HOST half of their codec's two-stage
    decode (``host_stage_decode``): the row carries a staging object (e.g. JPEG DCT
    coefficient planes) that the JAX loader finishes on device in one batched dispatch.
    ``prestaged`` supplies this row's already-staged payloads for device fields the
    caller batch-decoded at the row-group level (one native call for the whole group).
    """
    decoded = {}
    for name, field in schema.fields.items():
        if name not in row:
            continue
        value = row[name]
        if value is None:
            if not field.nullable:
                raise DecodeFieldError("Field %r is not nullable but stored value is None" % name)
            decoded[name] = None
        elif field.codec is not None:
            try:
                if name in device_fields:
                    if prestaged is not None and name in prestaged:
                        decoded[name] = prestaged[name]
                    else:
                        decoded[name] = field.codec.host_stage_decode(field, value)
                else:
                    decoded[name] = field.codec.decode(field, value)
            except Exception as e:  # noqa: BLE001 - annotate and rethrow
                raise DecodeFieldError("Unable to decode field %r: %s" % (name, e)) from e
        else:
            decoded[name] = _coerce_plain(field, value)
    return decoded


def _coerce_plain(field, value):
    """Coerce a codec-less stored value to the field's declared numpy dtype."""
    np_dtype = np.dtype(field.numpy_dtype)
    shape = field.shape or ()
    if len(shape) > 0:
        return np.asarray(value, dtype=None if np_dtype.kind == "O" else np_dtype)
    if np_dtype.kind in ("U", "S", "O"):
        return value
    if np_dtype.kind == "M":
        return np.datetime64(value) if value is not None else value
    if isinstance(value, np.ndarray) and value.ndim == 0:
        return value[()]
    return np_dtype.type(value)


def stack_as_column(values, force_object=False):
    """Pack per-row values into one column array: a stacked ndarray when rows are
    uniform, an object array otherwise (ragged rows, staging payloads, strings).

    ``force_object=True`` skips the stacking attempt — required for columns whose rows
    may MIX ndarrays and non-array payloads (e.g. device-decode staging objects with
    per-stream host fallbacks), where np.asarray would pick a layout per batch and
    downstream concatenation would break.
    """
    if not force_object:
        try:
            return np.asarray(values)
        except (ValueError, TypeError):
            pass
    arr = np.empty(len(values), dtype=object)
    arr[:] = values
    return arr


def pad_to_shape(array, shape, pad_value=0):
    """Pad/validate an array against a static-or-None shape tuple; used by the JAX loader to
    produce the fixed shapes XLA requires."""
    if len(shape) != array.ndim:
        raise ValueError(
            "Shape rank %d does not match array rank %d" % (len(shape), array.ndim)
        )
    target = tuple(s if s is not None else a for s, a in zip(shape, array.shape))
    if target == array.shape:
        return array
    pads = []
    for t, a in zip(target, array.shape):
        if a > t:
            raise ValueError("Array dim %d exceeds padded max %d" % (a, t))
        pads.append((0, t - a))
    return np.pad(array, pads, constant_values=pad_value)
