"""Unischema: one schema definition usable for writing (pyarrow/Spark) and reading (numpy/JAX).

Capability parity with the reference schema system (petastorm/unischema.py: ``UnischemaField``
~L40, ``Unischema`` ~L100, ``dict_to_spark_row`` ~L400, ``insert_explicit_nulls``,
``match_unischema_fields``), with TPU-first deltas:

- Self-describing JSON serialization (``to_json``/``from_json``) is the native metadata format,
  replacing the reference's pickled-class blob; the pickled ``UNISCHEMA_KEY`` written by real
  petastorm datasets is still *readable* via petastorm_tpu/compat/reference.py.
- The write path is pyarrow-native (``as_arrow_schema`` + ``dict_to_record``); Spark is an
  optional veneer (``as_spark_schema`` / ``dict_to_spark_row``) used only by the Spark converter.
- Fields declare static-or-padded shapes so the JAX loader can always produce fixed-shape device
  batches (XLA needs static shapes); ragged dims are ``None`` and must be resolved by a padding
  policy before device transfer.
"""
from __future__ import annotations

import re
from collections import OrderedDict, namedtuple
from typing import NamedTuple, Optional, Tuple

import numpy as np


class UnischemaField(NamedTuple):
    """A single field: name, numpy dtype, shape, codec, nullability.

    Field order matches the reference namedtuple (petastorm/unischema.py ~L40) so that pickled
    reference schemas unpickle onto this class via the compat unpickler.
    """

    name: str
    numpy_dtype: object
    shape: Optional[Tuple[Optional[int], ...]]
    codec: object = None
    nullable: bool = False

    def __hash__(self):
        return hash((self.name, str(np.dtype(self.numpy_dtype)), self.shape, self.nullable))

    def __eq__(self, other):
        if not isinstance(other, UnischemaField):
            return NotImplemented
        return (
            self.name == other.name
            and np.dtype(self.numpy_dtype) == np.dtype(other.numpy_dtype)
            and self.shape == other.shape
            and self.codec == other.codec
            and self.nullable == other.nullable
        )


class _NamedtupleCache:
    """Process-wide cache of row namedtuple types, keyed by (schema name, field names).

    Reference: ``Unischema._get_namedtuple`` caches per schema instance; caching process-wide
    keeps types identical across pickling boundaries (worker processes)."""

    _d = {}

    @classmethod
    def get(cls, parent_name, field_names):
        key = (parent_name, tuple(field_names))
        if key not in cls._d:
            cls._d[key] = namedtuple(parent_name + "_view", field_names, rename=False)
        return cls._d[key]


class Unischema:
    """Ordered collection of :class:`UnischemaField` (reference: petastorm/unischema.py ~L100)."""

    def __init__(self, name, fields):
        self._name = name
        for f in fields:
            if not isinstance(f, UnischemaField):
                raise ValueError("Expected UnischemaField, got %r" % (f,))
        names = [f.name for f in fields]
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ValueError("Duplicate field names in schema %r: %r" % (name, sorted(dupes)))
        self._fields = OrderedDict((f.name, f) for f in fields)

    # -- basic access -------------------------------------------------------------------

    @property
    def fields(self):
        return self._fields

    def __getattr__(self, name):
        fields = self.__dict__.get("_fields")
        if fields is not None and name in fields:
            return fields[name]
        raise AttributeError("Schema %r has no field %r" % (self.__dict__.get("_name"), name))

    def __getstate__(self):
        return self.__dict__

    def __setstate__(self, state):
        self.__dict__.update(state)

    def __repr__(self):
        lines = ["Unischema(%r, [" % self._name]
        for f in self._fields.values():
            lines.append("  %r," % (f,))
        lines.append("])")
        return "\n".join(lines)

    # -- views & selection --------------------------------------------------------------

    def create_schema_view(self, fields):
        """Subset view; ``fields`` may be UnischemaFields, names, or regex patterns.

        Reference: ``Unischema.create_schema_view`` (~L150) + ``match_unischema_fields``.
        """
        selected = []
        for f in fields:
            if isinstance(f, UnischemaField):
                ours = self._fields.get(f.name)
                if ours is None or ours != f:
                    raise ValueError(
                        "Field %r does not belong to schema %r (name, dtype, shape and codec "
                        "must all match)" % (f, self._name)
                    )
                selected.append(ours)
            elif isinstance(f, str):
                matched = match_unischema_fields(self, [f])
                if not matched:
                    raise ValueError(
                        "Field selector %r matched no fields of schema %r" % (f, self._name)
                    )
                selected.extend(matched)
            else:
                raise ValueError("Unexpected field selector %r" % (f,))
        # preserve schema order, dedupe
        names = {f.name for f in selected}
        ordered = [f for f in self._fields.values() if f.name in names]
        return Unischema(self._name, ordered)

    def make_namedtuple(self, **kwargs):
        """Build a row namedtuple from per-field kwargs (missing nullable fields -> None)."""
        typ = self.make_namedtuple_type()
        values = {}
        for name, field in self._fields.items():
            if name in kwargs:
                values[name] = kwargs[name]
            elif field.nullable:
                values[name] = None
            else:
                raise ValueError(
                    "Field %r is not nullable but missing from the row" % name
                )
        return typ(**values)

    def make_namedtuple_type(self):
        return _NamedtupleCache.get(self._name, list(self._fields.keys()))

    # -- arrow interop ------------------------------------------------------------------

    def as_arrow_schema(self):
        """Storage-level pyarrow schema (codec storage types, not logical tensor types)."""
        import pyarrow as pa

        pa_fields = []
        for f in self._fields.values():
            if f.codec is not None:
                typ = f.codec.arrow_dtype(f)
            else:
                typ = _numpy_to_arrow(f)
            pa_fields.append(pa.field(f.name, typ, nullable=bool(f.nullable)))
        return pa.schema(pa_fields)

    @classmethod
    def from_arrow_schema(cls, arrow_schema_or_dataset, omit_unsupported_fields=True):
        """Infer a codec-less Unischema from an Arrow schema (make_batch_reader path).

        Reference: ``Unischema.from_arrow_schema`` (petastorm/unischema.py ~L300).
        """
        import pyarrow as pa

        if isinstance(arrow_schema_or_dataset, pa.Schema):
            arrow_schema = arrow_schema_or_dataset
            name = "inferred"
        else:  # pyarrow.dataset.Dataset or parquet dataset
            arrow_schema = arrow_schema_or_dataset.schema
            if hasattr(arrow_schema, "to_arrow_schema"):
                arrow_schema = arrow_schema.to_arrow_schema()
            name = "inferred"
        fields = []
        for pa_field in arrow_schema:
            try:
                fields.append(_arrow_field_to_unischema_field(pa_field))
            except ValueError:
                if not omit_unsupported_fields:
                    raise
        return cls(name, fields)

    # -- spark interop (optional) -------------------------------------------------------

    def as_spark_schema(self):
        import pyspark.sql.types as T

        sql_fields = []
        for f in self._fields.values():
            if f.codec is None:
                from petastorm_tpu.types import tag_for_numpy_dtype

                spark_type = tag_for_numpy_dtype(f.numpy_dtype).spark_type()
            else:
                spark_type = f.codec.spark_dtype()
            sql_fields.append(T.StructField(f.name, spark_type, bool(f.nullable)))
        return T.StructType(sql_fields)

    # -- JSON metadata (native format) --------------------------------------------------

    def to_json(self):
        import json

        return json.dumps(
            {
                "name": self._name,
                "fields": [_field_to_jsonable(f) for f in self._fields.values()],
            }
        )

    @classmethod
    def from_json(cls, payload):
        import json

        obj = json.loads(payload)
        return cls(obj["name"], [_field_from_jsonable(d) for d in obj["fields"]])

    @property
    def name(self):
        return self._name


def match_unischema_fields(schema, field_regexes):
    """Fields of ``schema`` whose names fully match any regex (reference ~L500).

    Plain names behave as exact matches (they are valid regexes that fullmatch themselves).
    """
    matched = []
    compiled = [re.compile(p) for p in field_regexes]
    for f in schema.fields.values():
        if any(p.fullmatch(f.name) for p in compiled):
            matched.append(f)
    return matched


def insert_explicit_nulls(schema, row_dict):
    """Add ``None`` for nullable fields missing from ``row_dict`` (reference ~L450)."""
    for name, f in schema.fields.items():
        if name not in row_dict:
            if f.nullable:
                row_dict[name] = None
            else:
                raise ValueError("Field %r is not nullable but is missing from the row" % name)


def encode_row(schema, row_dict):
    """Encode a {field: value} dict through codecs into Parquet-storable values.

    This is the storage-agnostic core of the reference's ``dict_to_spark_row``
    (petastorm/unischema.py ~L400): same validation and codec dispatch, minus Spark ``Row``.
    """
    if not isinstance(row_dict, dict):
        raise TypeError("row must be a dict, got %r" % type(row_dict))
    unknown = set(row_dict.keys()) - set(schema.fields.keys())
    if unknown:
        raise ValueError("Fields %r not part of schema %r" % (sorted(unknown), schema.name))
    full = dict(row_dict)
    insert_explicit_nulls(schema, full)
    encoded = {}
    for name, field in schema.fields.items():
        value = full[name]
        if value is None:
            if not field.nullable:
                raise ValueError("Field %r is not nullable but got None" % name)
            encoded[name] = None
        elif field.codec is not None:
            encoded[name] = field.codec.encode(field, value)
        else:
            encoded[name] = value
    return encoded


def dict_to_record(schema, row_dict):
    """Alias of :func:`encode_row` (pyarrow write path)."""
    return encode_row(schema, row_dict)


def dict_to_spark_row(schema, row_dict):
    """Encode and wrap in a pyspark Row (requires pyspark). Reference API name kept."""
    from pyspark.sql import Row

    encoded = encode_row(schema, row_dict)
    # Row(**kwargs) sorts by key on old pyspark; build positionally to preserve schema order.
    cls = Row(*schema.fields.keys())
    return cls(*[_bytes_for_spark(encoded[name]) for name in schema.fields.keys()])


def _bytes_for_spark(value):
    return bytearray(value) if isinstance(value, bytes) else value


def _numpy_to_arrow(field):
    import pyarrow as pa

    np_dtype = np.dtype(field.numpy_dtype)
    shape = field.shape or ()
    if len(shape) == 0:
        if np_dtype.kind in ("U", "S", "O"):
            return pa.string()
        if np_dtype.kind == "M":
            return pa.timestamp("us")
        return pa.from_numpy_dtype(np_dtype)
    # codec-less tensor columns are stored as (nested) arrow lists
    typ = pa.from_numpy_dtype(np_dtype)
    for _ in shape:
        typ = pa.list_(typ)
    return typ


_ARROW_DECIMAL_KINDS = ("decimal128", "decimal256")


def _arrow_field_to_unischema_field(pa_field):
    import pyarrow as pa
    import pyarrow.types as pat

    typ = pa_field.type
    shape = ()
    depth = 0
    while pat.is_list(typ) or pat.is_large_list(typ) or pat.is_fixed_size_list(typ):
        size = typ.list_size if pat.is_fixed_size_list(typ) else None
        shape = shape + (size,)
        typ = typ.value_type
        depth += 1
    if pat.is_dictionary(typ):
        # dictionary encoding (pandas categoricals) is a storage detail: the field's
        # logical type is the dictionary's VALUE type — silently dropping the column
        # (the old behavior via the unsupported-type omit) loses data
        typ = typ.value_type
    if pat.is_decimal(typ):
        np_dtype = np.dtype("object")
    elif pat.is_string(typ) or pat.is_large_string(typ):
        np_dtype = np.dtype("object")
    elif pat.is_binary(typ) or pat.is_large_binary(typ):
        np_dtype = np.dtype("object")
    elif pat.is_date(typ):
        np_dtype = np.dtype("datetime64[D]")
    elif pat.is_timestamp(typ):
        np_dtype = np.dtype("datetime64[%s]" % typ.unit)
    elif pat.is_boolean(typ) or pat.is_integer(typ) or pat.is_floating(typ):
        np_dtype = np.dtype(typ.to_pandas_dtype())
    else:
        raise ValueError("Unsupported arrow type %r for field %r" % (typ, pa_field.name))
    return UnischemaField(pa_field.name, np_dtype, shape, None, pa_field.nullable)


def _field_to_jsonable(f):
    from petastorm_tpu import codecs as C
    from petastorm_tpu import types as ptypes

    codec = None
    if isinstance(f.codec, C.ScalarCodec):
        t = f.codec.scalar_type
        codec = {"kind": "scalar", "type": type(t).__name__}
        if isinstance(t, ptypes.DecimalType):
            codec.update(precision=t.precision, scale=t.scale)
    elif isinstance(f.codec, C.NdarrayCodec):
        codec = {"kind": "ndarray"}
    elif isinstance(f.codec, C.CompressedNdarrayCodec):
        codec = {"kind": "compressed_ndarray"}
    elif isinstance(f.codec, C.CompressedImageCodec):
        codec = {
            "kind": "image",
            "format": f.codec.image_codec,
            "quality": f.codec._quality,
        }
    elif f.codec is not None:
        raise ValueError("Cannot serialize custom codec %r to JSON metadata" % (f.codec,))
    return {
        "name": f.name,
        "numpy_dtype": np.dtype(f.numpy_dtype).str if np.dtype(f.numpy_dtype).kind != "O" else "object",
        "shape": list(f.shape) if f.shape is not None else None,
        "codec": codec,
        "nullable": bool(f.nullable),
    }


def _field_from_jsonable(d):
    from petastorm_tpu import codecs as C
    from petastorm_tpu import types as ptypes

    codec_desc = d.get("codec")
    codec = None
    if codec_desc:
        kind = codec_desc["kind"]
        if kind == "scalar":
            tname = codec_desc["type"]
            if tname == "DecimalType":
                tag = ptypes.DecimalType(codec_desc["precision"], codec_desc["scale"])
            else:
                tag = getattr(ptypes, tname)()
            codec = C.ScalarCodec(tag)
        elif kind == "ndarray":
            codec = C.NdarrayCodec()
        elif kind == "compressed_ndarray":
            codec = C.CompressedNdarrayCodec()
        elif kind == "image":
            codec = C.CompressedImageCodec(codec_desc["format"], codec_desc.get("quality", 80))
        else:
            raise ValueError("Unknown codec kind %r" % kind)
    dtype = d["numpy_dtype"]
    np_dtype = np.dtype("object") if dtype == "object" else np.dtype(dtype)
    shape = tuple(d["shape"]) if d["shape"] is not None else None
    return UnischemaField(d["name"], np_dtype, shape, codec, d["nullable"])
